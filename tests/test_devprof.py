"""Device-plane observability (ISSUE 18): kernel-time attribution from
profiler captures, the HBM memory ledger with OOM forensics, and
mesh/sharding introspection.

Acceptance: a CPU ``run_synthetic --profile-windows N`` run yields a
merged Chrome trace with at least one device lane beside the host
spans, a non-empty kernel table from ``tools/device_report.py --json``,
live ``/meshz`` and ``/kernelz`` responses, and a ``device.oom`` chaos
run whose crash dump carries the buffer census.
"""

import datetime
import gzip
import json
import os
import shutil
import sys
import urllib.request

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from kafka_tpu import telemetry  # noqa: E402
from kafka_tpu.telemetry import (  # noqa: E402
    MetricsRegistry, devprof, perf,
)
from kafka_tpu.telemetry.aggregate import stitch_traces  # noqa: E402
from kafka_tpu.resilience import faults  # noqa: E402

FIXTURE_CAPTURE = os.path.join(
    REPO_ROOT, "tests", "fixtures", "devprof_capture"
)
FIXTURE_SESSION = os.path.join(
    FIXTURE_CAPTURE, "plugins", "profile", "2026_08_07_00_00_00"
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def day(i):
    return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)


def run_identity_engine(telemetry_dir=None, scan_window=1):
    """Small identity-operator run (the shared engine harness shape of
    tests/test_perf.py).  Returns ``(kf, out, reg)``."""
    import jax.numpy as jnp

    from kafka_tpu.core.propagators import (
        PixelPrior, propagate_information_filter_approx,
    )
    from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
    from kafka_tpu.obsops.identity import IdentityOperator
    from kafka_tpu.testing.fixtures import make_pivot_mask
    from kafka_tpu.testing.synthetic import (
        MemoryOutput, SyntheticObservations,
    )

    mask = make_pivot_mask(20, 20, seed=0)
    p = 2
    op = IdentityOperator(n_params=p, obs_indices=(0, 1))
    cov = np.diag(np.full(p, 0.4 ** 2)).astype(np.float32)
    prior = FixedGaussianPrior(
        PixelPrior(
            mean=jnp.full((p,), 0.5, jnp.float32),
            cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        ),
        ("a", "b"),
    )
    truth = np.broadcast_to(
        np.array([0.3, 0.7], np.float32), mask.shape + (2,)
    ).astype(np.float32)
    with telemetry.use(MetricsRegistry(telemetry_dir)) as reg:
        obs = SyntheticObservations(
            dates=[day(i) for i in range(1, 16, 2)], operator=op,
            truth_fn=lambda d: truth, sigma=0.02, mask_prob=0.1, seed=0,
        )
        out = MemoryOutput()
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=propagate_information_filter_approx,
            prior=None, solver_options={"relaxation": 0.5},
            scan_window=scan_window, prefetch_depth=0,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.full(p, 1e-3, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        kf.run([day(i) for i in range(0, 20, 4)], x0, None, p_inv0)
    return kf, out, reg


# ---------------------------------------------------------------------------
# Kernel-time attribution: the capture parser on the checked-in fixture.
# ---------------------------------------------------------------------------

class TestCaptureParser:
    def test_fixture_parses_into_ranked_kernel_table(self):
        table = devprof.parse_capture(FIXTURE_SESSION)
        assert table is not None
        # Ranked by total ms, host python frames excluded.
        names = [k["name"] for k in table["kernels"]]
        assert names == [
            "broadcast_add_fusion", "dot.7", "all-reduce.1", "copy.3",
        ]
        assert "HostPythonFrame" not in names
        top = table["kernels"][0]
        assert top["bucket"] == "fusion"
        assert top["count"] == 2
        assert top["ms"] == pytest.approx(6.0)
        assert top["fraction"] == pytest.approx(6.0 / 10.5, abs=1e-3)
        assert table["device_ms"] == pytest.approx(10.5)
        assert table["by_bucket"] == {
            "collective": 1.5, "fusion": 6.0, "other": 2.5,
            "transfer": 0.5,
        }
        assert table["collective_fraction"] == pytest.approx(
            1.5 / 10.5, abs=1e-3
        )
        # The single host track carries all parsed device time.
        assert table["device_split"] == {"/host:CPU": 1.0}

    def test_bucket_vocabulary(self):
        assert devprof.bucket_for("loop_fusion.3") == "fusion"
        assert devprof.bucket_for("all-reduce.7") == "collective"
        assert devprof.bucket_for("AllGather.1") == "collective"
        assert devprof.bucket_for("copy-start.2") == "transfer"
        assert devprof.bucket_for("dot.9") == "other"

    def test_ingest_publishes_metrics_and_event(self, tmp_path):
        root = str(tmp_path / "profile")
        shutil.copytree(FIXTURE_CAPTURE, root)
        reg = MetricsRegistry()
        table = devprof.ingest_capture(root, registry=reg)
        assert table is not None
        assert reg.value("kafka_devprof_captures_parsed_total") == 1
        assert reg.value(
            "kafka_devprof_kernel_ms_total", bucket="fusion"
        ) == pytest.approx(6.0)
        assert reg.value(
            "kafka_devprof_kernel_ms_total", bucket="collective"
        ) == pytest.approx(1.5)
        assert reg.value(
            "kafka_devprof_collective_fraction"
        ) == pytest.approx(1.5 / 10.5, abs=1e-3)
        assert any(
            e["event"] == "devprof_capture_parsed" for e in reg.events
        )
        # The parsed state serves /kernelz immediately.
        ks = devprof.kernel_summary(reg, n=2)
        assert ks["captures_parsed"] == 1
        assert len(ks["kernels"]) == 2
        assert ks["kernels"][0]["name"] == "broadcast_add_fusion"

    def test_malformed_capture_degrades_with_counted_event(
            self, tmp_path):
        """A garbage .trace.json.gz (and an event-less one) increments
        the parse-failure counter and emits the event — never raises."""
        sess = tmp_path / "plugins" / "profile" / "2026_01_01"
        sess.mkdir(parents=True)
        (sess / "bad.trace.json.gz").write_bytes(b"not gzip at all")
        reg = MetricsRegistry()
        assert devprof.ingest_capture(str(tmp_path), registry=reg) is None
        assert reg.value("kafka_devprof_parse_failures_total") == 1
        assert any(
            e["event"] == "devprof_parse_failed" for e in reg.events
        )
        # Empty-but-valid trace: parseable JSON, no device spans.
        with gzip.open(sess / "bad.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": []}, f)
        assert devprof.ingest_capture(str(tmp_path), registry=reg) is None
        assert reg.value("kafka_devprof_parse_failures_total") == 2

    def test_no_captures_at_all_is_a_counted_failure(self, tmp_path):
        reg = MetricsRegistry()
        assert devprof.ingest_capture(
            str(tmp_path / "nowhere"), registry=reg
        ) is None
        assert reg.value("kafka_devprof_parse_failures_total") == 1

    def test_roofline_crosscheck_needs_both_sides(self):
        reg = MetricsRegistry()
        # No capture, no window: no cross-check.
        assert devprof.roofline_crosscheck(reg) is None
        rec = {"wall_s": 0.001, "chi2_per_band": [1.0]}
        perf.record_window(
            rec, n_valid=10, n_pad=16, n_params=2, n_bands=1,
            registry=reg,
        )
        assert devprof.roofline_crosscheck(reg) is None  # still no capture
        st = devprof._state_for(reg)
        with st.lock:
            st.device_ms = 10.5
            st.n_captures_parsed = 1
        rc = devprof.roofline_crosscheck(reg)
        assert rc is not None
        assert rc["measured_device_ms"] == pytest.approx(10.5)
        assert rc["component"] == "gn_full"
        assert rc["analytic_min_ms_per_window"] > 0
        assert rc["measured_over_analytic"] > 0


# ---------------------------------------------------------------------------
# Stitched-trace fold-in: device lanes on the shared epoch axis.
# ---------------------------------------------------------------------------

class TestDeviceLaneStitching:
    def _root_with_host_and_capture(self, tmp_path):
        root = str(tmp_path / "tel")
        os.makedirs(root)
        host = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "ts": 0.0,
                 "pid": 7, "tid": 0, "args": {"name": "host"}},
                {"name": "solve", "ph": "X", "ts": 100.0,
                 "dur": 400000.0, "pid": 7, "tid": 1, "args": {}},
            ],
            "otherData": {"epoch_unix_s": 1700000000.0,
                          "run_ids": ["r1"]},
        }
        with open(os.path.join(root, "trace.json"), "w") as f:
            json.dump(host, f)
        shutil.copytree(
            FIXTURE_CAPTURE, os.path.join(root, "profile")
        )
        return root

    def test_device_lane_beside_host_spans_epoch_aligned(
            self, tmp_path):
        root = self._root_with_host_and_capture(tmp_path)
        doc = stitch_traces(root)
        procs = {
            e["pid"]: e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        dev_pids = [p for p, n in procs.items()
                    if n.startswith("kafka_tpu device ")]
        assert len(dev_pids) == 1
        dev_pid = dev_pids[0]
        # The capture started 0.25 s after the host epoch and its
        # earliest device event sat at tick 1000 us: alignment pins
        # that first kernel to 0.25e6 us on the stitched axis.
        kernels = [
            e for e in doc["traceEvents"]
            if e.get("pid") == dev_pid and e.get("ph") == "X"
        ]
        assert kernels
        assert min(e["ts"] for e in kernels) == pytest.approx(
            250000.0, abs=1.0
        )
        by_name = {e["name"]: e for e in kernels}
        assert "broadcast_add_fusion" in by_name
        assert by_name["dot.7"]["args"]["hlo_op"] == "dot.7"
        # Host span untouched at its own epoch-relative position.
        host_spans = [
            e for e in doc["traceEvents"]
            if e.get("name") == "solve" and e.get("ph") == "X"
        ]
        assert host_spans[0]["ts"] == pytest.approx(100.0)
        # Sources index flags the device lane.
        dev_sources = [
            s for s in doc["otherData"]["sources"]
            if s.get("device_lane")
        ]
        assert len(dev_sources) == 1
        assert dev_sources[0]["pid"] == dev_pid
        assert dev_sources[0]["epoch_unix_s"] == pytest.approx(
            1700000000.25
        )

    def test_capture_only_root_still_stitches(self, tmp_path):
        """No host trace.json at all: device lanes still merge (pinned
        to their own epoch), the doc stays well-formed."""
        root = str(tmp_path / "tel")
        os.makedirs(root)
        shutil.copytree(
            FIXTURE_CAPTURE, os.path.join(root, "profile")
        )
        doc = stitch_traces(root)
        assert any(
            s.get("device_lane") for s in doc["otherData"]["sources"]
        )
        assert any(
            e.get("ph") == "X" for e in doc["traceEvents"]
        )

    def test_request_waterfall_skips_device_lanes(self, tmp_path):
        root = self._root_with_host_and_capture(tmp_path)
        doc = stitch_traces(root, request_id="req-1")
        assert not any(
            s.get("device_lane") for s in doc["otherData"]["sources"]
        )


# ---------------------------------------------------------------------------
# /profilez capture retention: keep-N with counted evictions.
# ---------------------------------------------------------------------------

class TestCaptureRetention:
    def _make_session(self, root, name, mtime):
        sess = os.path.join(root, name, "plugins", "profile", "t0")
        os.makedirs(sess)
        with gzip.open(os.path.join(sess, "h.trace.json.gz"), "wt") as f:
            json.dump({"traceEvents": []}, f)
        with open(os.path.join(root, name, "capture_meta.json"),
                  "w") as f:
            json.dump({"epoch_unix_s": float(mtime)}, f)
        os.utime(sess, (mtime, mtime))
        return sess

    def test_prune_keeps_newest_n_and_counts_evictions(self, tmp_path):
        root = str(tmp_path / "profile")
        for i in range(5):
            self._make_session(root, f"2026010{i}T000000",
                               1700000000 + i)
        reg = MetricsRegistry()
        evicted = perf.prune_captures(root, keep=2, registry=reg)
        assert evicted == 3
        left = devprof.find_capture_sessions(root)
        assert len(left) == 2
        assert all("20260103" in s or "20260104" in s for s in left)
        assert reg.value("kafka_perf_capture_evictions_total") == 3
        assert sum(
            1 for e in reg.events
            if e["event"] == "profile_capture_evicted"
        ) == 3
        # Evicted capture roots collapsed entirely (scaffolding and
        # epoch sidecars go with their sessions).
        assert sorted(os.listdir(root)) == [
            "20260103T000000", "20260104T000000",
        ]
        # Under the cap: no-op.
        assert perf.prune_captures(root, keep=2, registry=reg) == 0

    def test_profilez_capture_path_prunes_siblings(self, tmp_path,
                                                   monkeypatch):
        """The /profilez path (perf.capture) enforces retention over
        sibling timestamped capture dirs after each capture."""
        def fake_start(directory):
            os.makedirs(directory, exist_ok=True)
            with gzip.open(os.path.join(directory, "h.trace.json.gz"),
                           "wt") as f:
                json.dump({"traceEvents": []}, f)

        monkeypatch.setattr(perf, "_start_trace", fake_start)
        monkeypatch.setattr(perf, "_stop_trace", lambda: None)
        monkeypatch.setattr(perf, "CAPTURE_KEEP", 3)
        reg = MetricsRegistry()
        base = str(tmp_path / "profile")
        for i in range(5):
            d = os.path.join(base, f"2026010{i}T000000")
            perf.capture(0.0, d, registry=reg)
            os.utime(d, (1700000000 + i, 1700000000 + i))
        assert len(devprof.find_capture_sessions(base)) == 3
        assert reg.value("kafka_perf_capture_evictions_total") == 2


# ---------------------------------------------------------------------------
# HBM memory ledger: buffer census + headroom gauges + OOM forensics.
# ---------------------------------------------------------------------------

class TestMemoryLedger:
    def test_census_groups_live_arrays_by_shape_dtype(self):
        import jax.numpy as jnp

        keep = [jnp.zeros((64, 3), jnp.float32) for _ in range(3)]
        keep.append(jnp.zeros((128,), jnp.int32))
        census = devprof.buffer_census()
        assert census, "live arrays exist — census must see them"
        groups = {
            (g["shape"], g["dtype"]): g for g in census
        }
        key = (str((64, 3)), "float32")
        assert key in groups
        assert groups[key]["count"] >= 3
        assert groups[key]["bytes"] >= 3 * 64 * 3 * 4
        # Ranked by resident bytes.
        sizes = [g["bytes"] for g in census]
        assert sizes == sorted(sizes, reverse=True)
        del keep

    def test_update_ledger_publishes_gauges(self):
        import jax.numpy as jnp

        keep = jnp.ones((32, 4), jnp.float32)
        reg = MetricsRegistry()
        census = devprof.update_ledger(reg)
        assert census
        assert reg.value("kafka_devprof_live_buffer_bytes") > 0
        assert reg.value("kafka_devprof_live_buffers") >= 1
        del keep

    def test_is_oom_vocabulary(self):
        assert devprof.is_oom(
            faults.InjectedFault("device.oom", 1, "fatal")
        )
        assert devprof.is_oom(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                         "1073741824 bytes")
        )
        assert not devprof.is_oom(RuntimeError("shape mismatch"))
        assert not devprof.is_oom(None)
        assert not devprof.is_oom(
            faults.InjectedFault("io.read_band", 1, "transient")
        )

    def test_forensics_bundle_shape(self):
        import jax.numpy as jnp

        keep = jnp.ones((16, 2), jnp.float32)
        reg = MetricsRegistry()
        bundle = devprof.forensics(reg)
        assert set(bundle) == {"buffer_census", "kernel_table", "memory"}
        assert bundle["buffer_census"]
        assert isinstance(bundle["kernel_table"], list)
        del keep


class TestOOMForensics:
    def test_oom_chaos_run_dump_carries_buffer_census(self, tmp_path):
        """ISSUE 18 acceptance: a device.oom chaos run's crash dump
        names the resident buffers — the engine unwinds through the
        flight-recorder guard with the census attached."""
        from kafka_tpu.telemetry.flight_recorder import FlightRecorder

        tel = str(tmp_path / "tel")
        faults.script("device.oom", "1")
        with pytest.raises(faults.InjectedFault) as ei:
            with telemetry.use(MetricsRegistry(tel)):
                with FlightRecorder(tel):
                    run_identity_engine(telemetry_dir=None)
        assert ei.value.site == "device.oom"
        dumps = [f for f in os.listdir(tel) if f.startswith("crash_")]
        assert len(dumps) == 1
        rec = json.load(open(os.path.join(tel, dumps[0])))
        forensics = rec.get("device_forensics")
        assert forensics is not None
        assert forensics["buffer_census"], \
            "the dump must name the resident buffers"
        assert {"shape", "dtype", "sharding", "count", "bytes"} <= set(
            forensics["buffer_census"][0]
        )
        assert "kernel_table" in forensics and "memory" in forensics

    def test_non_oom_crash_has_no_forensics(self, tmp_path):
        from kafka_tpu.telemetry.flight_recorder import FlightRecorder

        tel = str(tmp_path / "tel")
        with telemetry.use(MetricsRegistry(tel)):
            rec = FlightRecorder(tel)
            rec.dump("exception", exc=ValueError("not an oom"))
        dumps = [f for f in os.listdir(tel) if f.startswith("crash_")]
        doc = json.load(open(os.path.join(tel, dumps[0])))
        assert "device_forensics" not in doc


# ---------------------------------------------------------------------------
# Mesh introspection: note_mesh / note_compiled / mesh_summary.
# ---------------------------------------------------------------------------

class TestMeshIntrospection:
    def test_mesh_summary_degrades_on_cpu(self):
        reg = MetricsRegistry()
        ms = devprof.mesh_summary(reg)
        assert ms["backend"] == "cpu"
        assert ms["n_devices"] >= 1
        assert ms["devices"][0]["platform"] == "cpu"
        assert ms["mesh"] is None
        assert ms["programs"] == {}

    def test_note_mesh_registers_axes(self):
        import jax
        from jax.sharding import Mesh

        reg = MetricsRegistry()
        mesh = Mesh(
            np.array(jax.devices()[:1]).reshape(1, 1), ("data", "model")
        )
        devprof.note_mesh(mesh, registry=reg)
        ms = devprof.mesh_summary(reg)
        assert ms["mesh"] == {
            "axes": {"data": 1, "model": 1}, "n_devices": 1,
        }

    def test_note_compiled_extracts_partition_specs(self):
        import jax
        import jax.numpy as jnp

        reg = MetricsRegistry()
        compiled = jax.jit(lambda x: x * 2).lower(
            jnp.zeros((8,), jnp.float32)
        ).compile()
        devprof.note_compiled("double", compiled, registry=reg)
        progs = devprof.mesh_summary(reg)["programs"]
        assert "double" in progs
        # Best-effort extraction: whatever this jax exposes is strings.
        for specs in progs["double"].values():
            assert all(isinstance(s, str) for s in specs)

    def test_engine_mesh_path_registers_intent(self):
        """KalmanFilter's mesh branch calls note_mesh: construct with a
        1-device mesh and read it back from the bound registry."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from kafka_tpu.core.propagators import (
            PixelPrior, propagate_information_filter_approx,
        )
        from kafka_tpu.engine import KalmanFilter
        from kafka_tpu.obsops.identity import IdentityOperator
        from kafka_tpu.testing.fixtures import make_pivot_mask
        from kafka_tpu.testing.synthetic import (
            MemoryOutput, SyntheticObservations,
        )

        mask = make_pivot_mask(8, 8, seed=0)
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        mesh = Mesh(np.array(jax.devices()[:1]), ("devices",))
        with telemetry.use(MetricsRegistry()) as reg:
            obs = SyntheticObservations(
                dates=[day(1)], operator=op,
                truth_fn=lambda d: np.zeros(
                    mask.shape + (2,), np.float32
                ),
                sigma=0.02, mask_prob=0.1, seed=0,
            )
            KalmanFilter(
                obs, MemoryOutput(), mask, ("a", "b"),
                state_propagation=propagate_information_filter_approx,
                prior=None, mesh=mesh, prefetch_depth=0,
            )
            ms = devprof.mesh_summary(reg)
        assert ms["mesh"] is not None
        assert ms["mesh"]["axes"] == {"devices": 1}


# ---------------------------------------------------------------------------
# Endpoints: /kernelz and /meshz are live (before and after a capture).
# ---------------------------------------------------------------------------

class TestEndpoints:
    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=30) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    def test_kernelz_live_before_any_capture(self):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        reg = MetricsRegistry()
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/kernelz?json=1")
            assert code == 200
            payload = json.loads(body)
            assert payload["captures_parsed"] == 0
            assert payload["kernels"] == []
            code, text = self._get(httpd.url + "/kernelz")
            assert code == 200 and "no capture parsed yet" in text
        finally:
            httpd.close()

    def test_kernelz_serves_parsed_table(self, tmp_path):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        root = str(tmp_path / "profile")
        shutil.copytree(FIXTURE_CAPTURE, root)
        reg = MetricsRegistry()
        devprof.ingest_capture(root, registry=reg)
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/kernelz?json=1&n=2")
            assert code == 200
            payload = json.loads(body)
            assert payload["captures_parsed"] == 1
            assert [k["name"] for k in payload["kernels"]] == [
                "broadcast_add_fusion", "dot.7",
            ]
            code, text = self._get(httpd.url + "/kernelz")
            assert "broadcast_add_fusion" in text
            assert "collective" in text
        finally:
            httpd.close()

    def test_meshz_live_and_in_index(self):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        reg = MetricsRegistry()
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/meshz?json=1")
            assert code == 200
            payload = json.loads(body)
            assert payload["backend"] == "cpu"
            assert payload["n_devices"] >= 1
            code, text = self._get(httpd.url + "/meshz")
            assert code == 200 and "backend=cpu" in text
            code, body = self._get(httpd.url + "/")
            endpoints = json.loads(body)["endpoints"]
            assert "/kernelz" in endpoints and "/meshz" in endpoints
        finally:
            httpd.close()

    def test_statusz_carries_devprof(self):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        reg = MetricsRegistry()
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/statusz")
            assert code == 200
            snap = json.loads(body)["devprof"]
            assert snap["captures_parsed"] == 0
            assert "live_buffer_bytes" in snap
        finally:
            httpd.close()


# ---------------------------------------------------------------------------
# Acceptance: CPU run_synthetic --profile-windows end to end.
# ---------------------------------------------------------------------------

class TestAcceptance:
    def test_profile_windows_yields_device_lane_and_kernel_table(
            self, tmp_path):
        """The full ISSUE 18 loop on CPU with the REAL profiler: the
        driver's windowed capture parses into a kernel table, the
        stitched trace grows a device lane beside the host spans,
        device_report --json is non-empty, and /kernelz + /meshz answer
        live off the run's registry."""
        from kafka_tpu.telemetry import get_registry, set_registry
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd
        from kafka_tpu.cli.run_synthetic import main
        from tools.device_report import build_report

        tel = str(tmp_path / "tel")
        prev = get_registry()
        try:
            main([
                "--operator", "identity", "--ny", "24", "--nx", "24",
                "--days", "8", "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
                "--profile-windows", "2",
            ])
            reg = get_registry()
            # The capture parsed into the kernel table at stop time.
            assert reg.value("kafka_devprof_captures_parsed_total") == 1
            ks = devprof.kernel_summary(reg)
            assert ks["kernels"], "CPU capture must yield XLA kernels"
            assert ks["device_ms"] > 0
            # Live endpoints off the run's registry.
            httpd = TelemetryHTTPd(port=0, registry=reg).start()
            try:
                with urllib.request.urlopen(
                        httpd.url + "/kernelz?json=1", timeout=30
                ) as resp:
                    kz = json.load(resp)
                assert kz["captures_parsed"] == 1 and kz["kernels"]
                with urllib.request.urlopen(
                        httpd.url + "/meshz?json=1", timeout=30
                ) as resp:
                    mz = json.load(resp)
                assert mz["backend"] == "cpu"
                assert mz["device_time_split"]
            finally:
                httpd.close()
        finally:
            set_registry(prev)
            perf.stop_windowed_capture()
        # Stitched trace: >= 1 device lane beside the host spans.
        doc = stitch_traces(tel)
        dev_sources = [
            s for s in doc["otherData"]["sources"]
            if s.get("device_lane")
        ]
        host_sources = [
            s for s in doc["otherData"]["sources"]
            if not s.get("device_lane")
        ]
        assert dev_sources, "merged trace must carry a device lane"
        assert host_sources, "host trace.json fragments must be there"
        assert dev_sources[0]["epoch_unix_s"] is not None, \
            "the epoch sidecar must anchor the device lane"
        dev_pids = {s["pid"] for s in dev_sources}
        assert any(
            e.get("ph") == "X" and e.get("pid") in dev_pids
            for e in doc["traceEvents"]
        )
        # tools/device_report.py --json path: non-empty kernel table.
        report = build_report(tel)
        assert report["n_sessions"] >= 1
        assert report["sessions"][0]["parseable"]
        assert report["sessions"][0]["kernels"]
        # Live snapshot carried the devprof summary for the fleet view.
        snaps = [f for f in os.listdir(tel) if f.startswith("live_")]
        assert snaps
        snap = json.load(open(os.path.join(tel, snaps[0])))
        assert "devprof" in snap
