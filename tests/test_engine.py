"""End-to-end engine tests: full ``run()`` on synthetic data, no rasters —
the finished version of the reference's testing intent (SURVEY.md §4 (b)).
"""

import datetime
import os

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_tpu.core import propagate_information_filter
from kafka_tpu.engine import (
    Checkpointer,
    KalmanFilter,
    FixedGaussianPrior,
    make_pixel_gather,
)
from kafka_tpu.core.propagators import PixelPrior
from kafka_tpu.obsops import IdentityOperator, TwoStreamOperator
from kafka_tpu.testing import MemoryOutput, SyntheticObservations

RNG = np.random.default_rng(11)


def day(i):
    return datetime.datetime(2021, 1, 1) + datetime.timedelta(days=i)


def circle_mask(ny=20, nx=24, r=8):
    yy, xx = np.mgrid[:ny, :nx]
    return (yy - ny / 2) ** 2 + (xx - nx / 2) ** 2 < r**2


def gaussian_prior(p, mean, sigma):
    mean = np.full((p,), mean, np.float32)
    cov = np.diag(np.full((p,), sigma**2)).astype(np.float32)
    return PixelPrior(
        mean=jnp.asarray(mean), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(np.linalg.inv(cov)),
    )


class TestIdentityEndToEnd:
    def test_identity_filter_tracks_constant_truth(self):
        """Identity operator observing both params directly: after several
        dates the analysis must approach the constant truth and uncertainty
        must shrink monotonically."""
        mask = circle_mask()
        p = 2
        op = IdentityOperator(n_params=p, obs_indices=(0, 1))
        truth = RNG.uniform(0.3, 0.7, size=mask.shape + (p,)).astype(
            np.float32
        )
        obs = SyntheticObservations(
            dates=[day(i) for i in range(1, 9)],
            operator=op,
            truth_fn=lambda date: truth,
            sigma=0.05,
            mask_prob=0.15,
        )
        out = MemoryOutput()
        prior = FixedGaussianPrior(
            gaussian_prior(p, 0.5, 0.5), ("a", "b")
        )
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=propagate_information_filter,
            prior=None,
            pad_multiple=128,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.zeros(p, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        grid = [day(0), day(3), day(6), day(9)]
        x_a, p_a, p_inv_a = kf.run(grid, x0, None, p_inv0)

        # Outputs written for every grid step after the first
        assert sorted(out.output.keys()) == grid[1:]
        final = out.output[grid[-1]]
        err = np.abs(final["a"][mask] - truth[..., 0][mask]).mean()
        assert err < 0.02, err
        # Sigma must shrink as observations accumulate
        sig_first = out.output[grid[1]]["a_unc"][mask].mean()
        sig_last = final["a_unc"][mask].mean()
        assert sig_last < sig_first
        # Unmasked pixels untouched (scatter fill 0)
        assert np.all(final["a"][~mask] == 0.0)

    def test_no_observation_windows_keep_state(self):
        mask = circle_mask(10, 10, 4)
        p = 2
        op = IdentityOperator(n_params=p, obs_indices=(0, 1))
        truth = np.full(mask.shape + (p,), 0.6, np.float32)
        obs = SyntheticObservations(
            dates=[day(1)], operator=op,
            truth_fn=lambda date: truth, sigma=0.02, mask_prob=0.0,
        )
        out = MemoryOutput()
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=propagate_information_filter,
            pad_multiple=128,
        )
        kf.set_trajectory_uncertainty(np.zeros(p))
        prior = FixedGaussianPrior(gaussian_prior(p, 0.5, 0.3), ("a", "b"))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        grid = [day(0), day(2), day(4), day(6)]
        kf.run(grid, x0, None, p_inv0)
        # With Q=0 and no new obs, the state is simply carried forward.
        a2 = out.output[day(2)]["a"][mask]
        a6 = out.output[day(6)]["a"][mask]
        np.testing.assert_allclose(a2, a6, atol=1e-6)


class TestTwoStreamEndToEnd:
    def test_tip_pipeline_with_prior_advance(self):
        """The MODIS-style pipeline: two-stream operator, prior-only advance
        (state_propagation=None + prior, as the S2/MODIS-dask drivers use,
        kafka_test_Py36.py:159-187)."""
        from kafka_tpu.core import tip_prior
        from kafka_tpu.engine.priors import jrc_prior, TIP_PARAMETER_LIST

        mask = circle_mask(12, 12, 5)
        op = TwoStreamOperator()
        base = np.asarray(tip_prior().mean)
        truth = np.broadcast_to(
            base, mask.shape + (7,)
        ).copy()
        truth[..., 6] = 0.45
        # sigma must be small: at the dark-leaf TIP prior the albedo
        # sensitivity to TLAI is only ~0.03/unit, so obs noise maps to TLAI
        # spread as sigma/0.03 — 0.001 keeps the posterior tight.
        obs = SyntheticObservations(
            dates=[day(i) for i in (1, 2, 4, 5)],
            operator=op,
            truth_fn=lambda date: truth,
            sigma=0.001,
            mask_prob=0.05,
        )
        out = MemoryOutput()
        # Tighten the spectral/soil slots of the JRC prior so the 2-band
        # signal is attributed to TLAI (the untightened 7-param problem is
        # genuinely ambiguous — see test_obsops for the same physics).
        base_prior = jrc_prior()
        mean = np.asarray(base_prior.prior.mean)
        sigma = np.full(7, 0.01, np.float32)
        sigma[6] = 0.5
        cov = np.diag(sigma**2).astype(np.float32)
        tight = PixelPrior(
            mean=jnp.asarray(mean), cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        )
        prior = FixedGaussianPrior(tight, TIP_PARAMETER_LIST)
        kf = KalmanFilter(
            obs, out, mask, TIP_PARAMETER_LIST,
            state_propagation=None, prior=prior, pad_multiple=128,
            solver_options={"relaxation": 0.7, "max_iterations": 40},
        )
        kf.set_trajectory_uncertainty(np.zeros(7))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        grid = [day(0), day(3), day(6)]
        x_a, _, p_inv_a = kf.run(grid, x0, None, p_inv0)
        tlai = out.output[day(6)]["TeLAI"][mask]
        # Pixels pulled from prior TLAI (exp(-1) ~ 0.368) towards 0.45
        assert np.mean(tlai > 0.37) > 0.9
        assert np.abs(tlai - 0.45).mean() < 0.04
        assert kf.diagnostics_log, "diagnostics should be recorded"


class TestCheckpointResume:
    def test_checkpoint_roundtrip_and_resume(self, tmp_path):
        mask = circle_mask(10, 10, 4)
        p = 2
        op = IdentityOperator(n_params=p, obs_indices=(0, 1))
        truth = np.full(mask.shape + (p,), 0.4, np.float32)
        dates = [day(i) for i in range(1, 7)]

        def build(outdir):
            obs = SyntheticObservations(
                dates=dates, operator=op,
                truth_fn=lambda date: truth, sigma=0.03, seed=5,
            )
            out = MemoryOutput()
            kf = KalmanFilter(
                obs, out, mask, ("a", "b"),
                state_propagation=propagate_information_filter,
                pad_multiple=128,
            )
            # Nonzero Q so a resume that skipped the advance would diverge
            # from the uninterrupted run.
            kf.set_trajectory_uncertainty(np.full(p, 0.05, np.float32))
            return kf, out

        prior = FixedGaussianPrior(gaussian_prior(p, 0.5, 0.3), ("a", "b"))
        grid = [day(0), day(2), day(4), day(6)]

        # Full run with checkpointing
        kf, out_full = build(tmp_path)
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        ck = Checkpointer(str(tmp_path / "ck"))
        kf.run(grid, x0, None, p_inv0, checkpointer=ck)
        assert len(ck.list_checkpoints()) == 3

        # Simulate a crash after day(2): resume from that checkpoint
        ck2 = Checkpointer(str(tmp_path / "ck2"))
        kf2, out_partial = build(tmp_path)
        kf2.run([day(0), day(2)], x0, None, p_inv0, checkpointer=ck2)
        resumed_grid, seed = ck2.resume_time_grid(grid)
        assert resumed_grid == [day(2), day(4), day(6)]
        x_r, p_inv_r = seed
        kf3, out_resumed = build(tmp_path)
        kf3.run(resumed_grid, x_r, None, jnp.asarray(p_inv_r),
                advance_first=True)

        # The resumed run must reproduce the full run's final analysis
        # (observation draws are seeded identically).
        a_full = out_full.output[day(6)]["a"]
        a_res = out_resumed.output[day(6)]["a"]
        np.testing.assert_allclose(a_res, a_full, atol=1e-5)


class TestHessianCorrectionWiring:
    def test_correction_changes_information_not_state(self):
        """hessian_correction=True must flow through the engine to the
        solver (linear_kf.py:412-416 semantics): identical analysis state,
        different posterior information for a nonlinear operator."""
        from kafka_tpu.core import tip_prior
        from kafka_tpu.engine.priors import TIP_PARAMETER_LIST

        mask = circle_mask(8, 8, 3)
        op = TwoStreamOperator()
        base = np.asarray(tip_prior().mean)
        truth = np.broadcast_to(base, mask.shape + (7,)).copy()
        truth[..., 6] = 0.5
        prior = FixedGaussianPrior(tip_prior(), TIP_PARAMETER_LIST)

        def build(hessian_correction):
            obs = SyntheticObservations(
                dates=[day(1)], operator=op,
                truth_fn=lambda date: truth, sigma=0.01, mask_prob=0.0,
                seed=5,
            )
            out = MemoryOutput()
            kf = KalmanFilter(
                obs, out, mask, TIP_PARAMETER_LIST,
                state_propagation=None, prior=prior, pad_multiple=64,
                hessian_correction=hessian_correction,
            )
            x0, p_inv0 = prior.process_prior(None, kf.gather)
            x_a, _, p_inv_a = kf.run([day(0), day(2)], x0, None, p_inv0)
            return np.asarray(x_a), np.asarray(p_inv_a)

        x_plain, p_inv_plain = build(False)
        x_corr, p_inv_corr = build(True)
        np.testing.assert_allclose(x_corr, x_plain, atol=1e-6)
        assert np.isfinite(p_inv_corr).all()
        # Nonlinear operator + nonzero innovations -> a real correction.
        assert np.abs(p_inv_corr - p_inv_plain).max() > 1e-6


class TestCheckpointStorage:
    """Packed-triangle + sharded checkpoint format (scale fix: a full
    (n, p, p) dump is ~48 GB/step at the 10980**2/p=10 north star)."""

    def _state(self, n=37, p=5, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(n, p)).astype(np.float32)
        m = rng.normal(size=(n, p, p)).astype(np.float32)
        p_inv = m @ m.transpose(0, 2, 1) + 3 * np.eye(p, dtype=np.float32)
        return x, p_inv

    def test_pack_unpack_roundtrip(self):
        from kafka_tpu.engine.checkpoint import pack_tril, unpack_tril
        _, p_inv = self._state()
        packed = pack_tril(p_inv)
        assert packed.shape == (37, 15)
        np.testing.assert_array_equal(unpack_tril(packed, 5), p_inv)

    def test_storage_is_triangular_not_full(self, tmp_path):
        x, p_inv = self._state()
        ck = Checkpointer(str(tmp_path))
        (path,) = ck.save(day(1), x, p_inv)
        data = np.load(path)
        assert "p_analysis_inverse" not in data
        assert data["p_inv_tril"].shape == (37, 15)

    def test_sharded_roundtrip(self, tmp_path):
        x, p_inv = self._state()
        ck = Checkpointer(str(tmp_path), n_shards=4)
        paths = ck.save(day(3), x, p_inv)
        assert len(paths) == 4
        ts, x_l, p_inv_l = ck.load_latest()
        assert ts == day(3)
        np.testing.assert_array_equal(x_l, x)
        np.testing.assert_allclose(p_inv_l, p_inv, atol=1e-7)

    def test_incomplete_shard_set_ignored(self, tmp_path):
        x, p_inv = self._state()
        ck = Checkpointer(str(tmp_path), n_shards=3)
        ck.save(day(1), x, p_inv)
        paths = ck.save(day(2), x + 1, p_inv)
        os.remove(paths[1])  # crash mid-save of the day-2 checkpoint
        ts, x_l, _ = ck.load_latest()
        assert ts == day(1)
        np.testing.assert_array_equal(x_l, x)

    def test_none_information(self, tmp_path):
        x, _ = self._state()
        ck = Checkpointer(str(tmp_path), n_shards=2)
        ck.save(day(1), x, None)
        ts, x_l, p_inv_l = ck.load_latest()
        assert p_inv_l is None
        np.testing.assert_array_equal(x_l, x)

    def test_loads_round1_full_matrix_layout(self, tmp_path):
        x, p_inv = self._state()
        np.savez_compressed(
            tmp_path / "state_20170101T000000.npz",
            x_analysis=x, p_analysis_inverse=p_inv,
        )
        ck = Checkpointer(str(tmp_path))
        ts, x_l, p_inv_l = ck.load_latest()
        np.testing.assert_allclose(p_inv_l, p_inv, atol=1e-7)


class TestProfilerHooks:
    def test_profile_dir_produces_trace(self, tmp_path):
        """profile_dir must yield a jax.profiler trace on disk (SURVEY §5:
        the reference has no tracing at all)."""
        mask = circle_mask(8, 8, 3)
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        truth = np.full(mask.shape + (2,), 0.4, np.float32)
        obs = SyntheticObservations(
            dates=[day(1)], operator=op,
            truth_fn=lambda date: truth, sigma=0.05, seed=1,
        )
        kf = KalmanFilter(
            obs, MemoryOutput(), mask, ("a", "b"), pad_multiple=64,
            prior=FixedGaussianPrior(gaussian_prior(2, 0.5, 0.3),
                                     ("a", "b")),
        )
        x0, p_inv0 = kf.prior.process_prior(None, kf.gather)
        logdir = tmp_path / "prof"
        kf.run([day(0), day(2)], x0, None, p_inv0,
               profile_dir=str(logdir))
        traces = list(logdir.rglob("*.xplane.pb")) + \
            list(logdir.rglob("*.trace.json*"))
        assert traces, f"no trace files under {logdir}"


def _ck_state(n=37, p=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, p)).astype(np.float32)
    m = rng.normal(size=(n, p, p)).astype(np.float32)
    p_inv = m @ m.transpose(0, 2, 1) + 3 * np.eye(p, dtype=np.float32)
    return x, p_inv


def test_mixed_shard_counts_never_combine(tmp_path):
    """Leftover shards from a run with a different n_shards must not be
    mixed into one set (silent pixel duplication/corruption)."""
    x, p_inv = _ck_state()
    Checkpointer(str(tmp_path), n_shards=2).save(day(1), x, p_inv)
    # A rerun with n_shards=3 crashes after one shard of day(1)...
    paths3 = Checkpointer(str(tmp_path), n_shards=3).save(
        day(1), x + 9, p_inv
    )
    os.remove(paths3[0])
    os.remove(paths3[2])
    # ...the intact 2-shard set still loads, unpolluted.
    ts, x_l, _ = Checkpointer(str(tmp_path)).load_latest()
    assert ts == day(1)
    np.testing.assert_array_equal(x_l, x)


def test_complete_rewrite_with_new_shard_count_wins(tmp_path):
    x, p_inv = _ck_state()
    Checkpointer(str(tmp_path), n_shards=2).save(day(1), x, p_inv)
    Checkpointer(str(tmp_path), n_shards=3).save(day(1), x + 9, p_inv)
    _, x_l, _ = Checkpointer(str(tmp_path)).load_latest()
    np.testing.assert_array_equal(x_l, x + 9)


def test_load_single_shard(tmp_path):
    x, p_inv = _ck_state()
    ck = Checkpointer(str(tmp_path), n_shards=4)
    ck.save(day(1), x, p_inv)
    bounds = np.linspace(0, x.shape[0], 5).astype(int)
    ts, x_s, p_inv_s = ck.load_latest(shard=2)
    np.testing.assert_array_equal(x_s, x[bounds[2]:bounds[3]])
    np.testing.assert_allclose(
        p_inv_s, p_inv[bounds[2]:bounds[3]], atol=1e-7
    )


class TestRepadOnResume:
    def test_run_repads_foreign_padding(self):
        """A state checkpointed under a different padding (pre-mesh file,
        or a different local device count) must re-pad on run(), not fail
        with a shape mismatch (round-3 review finding)."""
        mask = circle_mask(10, 10, 4)
        p = 2
        op = IdentityOperator(n_params=p, obs_indices=(0, 1))
        truth = np.full(mask.shape + (p,), 0.6, np.float32)

        def build():
            obs = SyntheticObservations(
                dates=[day(1), day(2)], operator=op,
                truth_fn=lambda date: truth, sigma=0.02, mask_prob=0.0,
            )
            out = MemoryOutput()
            kf = KalmanFilter(
                obs, out, mask, ("a", "b"),
                state_propagation=propagate_information_filter,
                pad_multiple=128,
            )
            kf.set_trajectory_uncertainty(np.zeros(p))
            return kf, out

        prior = FixedGaussianPrior(gaussian_prior(p, 0.5, 0.3), ("a", "b"))
        kf_ref, out_ref = build()
        x0, p_inv0 = prior.process_prior(None, kf_ref.gather)
        assert kf_ref.gather.n_pad == 128
        grid = [day(0), day(3)]
        kf_ref.run(grid, x0, None, p_inv0)

        # The same valid pixels under a foreign 64-row padding.
        n_valid = kf_ref.gather.n_valid
        assert n_valid <= 64
        x0_64 = np.asarray(x0)[:64]
        p_inv0_64 = np.asarray(p_inv0)[:64]
        kf_f, out_f = build()
        kf_f.run(grid, x0_64, None, p_inv0_64)
        for key in out_ref.output[day(3)]:
            np.testing.assert_allclose(
                out_f.output[day(3)][key], out_ref.output[day(3)][key],
                atol=1e-6,
            )

    def test_run_rejects_state_smaller_than_mask(self):
        mask = circle_mask(10, 10, 4)
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        obs = SyntheticObservations(
            dates=[day(1)], operator=op,
            truth_fn=lambda date: np.full(mask.shape + (2,), 0.5,
                                          np.float32),
            sigma=0.02,
        )
        kf = KalmanFilter(
            obs, MemoryOutput(), mask, ("a", "b"), pad_multiple=128,
        )
        too_small = np.zeros((4, 2), np.float32)
        with pytest.raises(ValueError, match="valid pixels"):
            kf.run([day(0), day(2)], too_small, None,
                   np.broadcast_to(np.eye(2, dtype=np.float32),
                                   (4, 2, 2)).copy())
