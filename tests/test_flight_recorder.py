"""The crash flight recorder (ISSUE 3 tentpole): dump contents, the
mid-run-exception acceptance path, signal handling, and the unhealthy
health-probe trigger."""

import glob
import json
import os
import signal
import sys

import numpy as np
import pytest

from kafka_tpu import telemetry
from kafka_tpu.telemetry import MetricsRegistry, flight_recorder, tracing
from kafka_tpu.telemetry.flight_recorder import FlightRecorder


def crash_files(directory):
    return sorted(glob.glob(os.path.join(str(directory), "crash_*.json")))


class TestDump:
    def test_dump_carries_events_metrics_context_threads(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            reg.counter("kafka_test_total").inc(3)
            reg.emit("solve", date="2021-01-01", n_iterations=4)
            rec = FlightRecorder(str(tmp_path))
            with tracing.push(run_id="rr", chunk_id="0001"):
                path = rec.dump("sigterm")
        dump = json.load(open(path))
        assert dump["reason"] == "sigterm"
        assert dump["context"]["run_id"] == "rr"
        assert dump["context"]["chunk_id"] == "0001"
        assert dump["metrics"]["kafka_test_total"] == 3
        assert any(e["event"] == "solve" for e in dump["events"])
        names = {t["name"] for t in dump["threads"]}
        assert "MainThread" in names
        # The crash path also flushes the run's normal exports.
        assert os.path.exists(tmp_path / "metrics.json")

    def test_no_destination_no_dump(self):
        with telemetry.use(MetricsRegistry()):
            rec = FlightRecorder(None)
            assert rec.dump("exception", exc=RuntimeError("x")) is None

    def test_same_exception_dumped_once(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            rec = FlightRecorder(str(tmp_path))
            exc = RuntimeError("boom")
            assert rec.dump("exception", exc=exc) is not None
            assert rec.dump("exception", exc=exc) is None
        assert len(crash_files(tmp_path)) == 1


class TestMidRunException:
    def test_engine_crash_mid_run_dumps_flight_record(self, tmp_path):
        """ISSUE 3 acceptance: an exception injected mid-run (a reader
        that dies on the third date, raised through the prefetch thread
        into the engine loop) leaves crash_*.json with the last events
        and the final metric values."""
        import datetime

        import jax.numpy as jnp

        from kafka_tpu.core.propagators import PixelPrior
        from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
        from kafka_tpu.obsops.identity import IdentityOperator
        from kafka_tpu.testing import MemoryOutput, SyntheticObservations

        class Boom(RuntimeError):
            pass

        def day(i):
            return datetime.datetime(2021, 3, 1) + \
                datetime.timedelta(days=i)

        mask = np.ones((6, 6), bool)
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        truth = np.full(mask.shape + (2,), 0.5, np.float32)

        class DyingObservations(SyntheticObservations):
            def get_observations(self, date, gather):
                if date >= day(5):
                    raise Boom(f"reader died at {date}")
                return super().get_observations(date, gather)

        obs = DyingObservations(
            dates=[day(1), day(3), day(5), day(7)], operator=op,
            truth_fn=lambda date: truth, sigma=0.02,
        )
        mean = np.full((2,), 0.5, np.float32)
        cov = np.diag(np.full((2,), 0.25)).astype(np.float32)
        prior = FixedGaussianPrior(
            PixelPrior(
                mean=jnp.asarray(mean), cov=jnp.asarray(cov),
                inv_cov=jnp.asarray(np.linalg.inv(cov)),
            ),
            ("a", "b"),
        )
        tel = tmp_path / "tel"
        with telemetry.use(MetricsRegistry(str(tel))) as reg:
            rec = FlightRecorder(str(tel))
            kf = KalmanFilter(
                obs, MemoryOutput(), mask, ("a", "b"),
                state_propagation=None, prior=prior,
                pad_multiple=16, scan_window=1,
            )
            kf.set_trajectory_model()
            kf.set_trajectory_uncertainty(np.zeros(2, np.float32))
            x0, p_inv0 = prior.process_prior(None, kf.gather)
            with pytest.raises(Boom):
                with tracing.push(run_id="crashrun"), rec:
                    kf.run(
                        [day(0), day(2), day(4), day(6), day(8)],
                        x0, None, p_inv0,
                    )
            reads_at_death = reg.value("kafka_engine_device_reads_total")
        files = crash_files(tel)
        assert len(files) == 1
        dump = json.load(open(files[0]))
        assert dump["reason"] == "exception"
        assert dump["exception"]["type"] == "Boom"
        assert "reader died" in dump["exception"]["message"]
        assert dump["context"]["run_id"] == "crashrun"
        # The last events before death: the two successfully assimilated
        # dates' solves and their phases are in the ring.
        kinds = [e["event"] for e in dump["events"]]
        assert kinds.count("solve") == 2
        assert "phase" in kinds
        # Final metric values at the moment of death.
        assert dump["metrics"]["kafka_engine_device_reads_total"] == \
            reads_at_death == 2
        assert "kafka_prefetch_reads_total" in dump["metrics"]
        # The trace timeline survived the crash alongside the dump.
        assert os.path.exists(tel / "trace.json")

    def test_run_synthetic_crash_writes_dump(self, tmp_path, monkeypatch):
        """Driver-level acceptance: run_synthetic with --telemetry-dir
        dies mid-run -> crash_*.json lands in the telemetry dir."""
        from kafka_tpu.cli import run_synthetic
        from kafka_tpu.io import GeoTIFFOutput

        calls = {"n": 0}
        orig = GeoTIFFOutput.dump_data

        def dying_dump(self, *a, **kw):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise RuntimeError("disk on fire")
            return orig(self, *a, **kw)

        monkeypatch.setattr(GeoTIFFOutput, "dump_data", dying_dump)
        # Force the unfused path so dump_data (not dump_block) runs.
        monkeypatch.setattr(
            run_synthetic.KalmanFilter, "_fusion_possible",
            lambda self: False,
        )
        tel = str(tmp_path / "tel")
        prev = telemetry.get_registry()
        try:
            with pytest.raises(RuntimeError, match="disk on fire"):
                run_synthetic.main([
                    "--operator", "identity",
                    "--outdir", str(tmp_path / "out"),
                    "--telemetry-dir", tel,
                    "--days", "8", "--step", "2",
                    "--ny", "16", "--nx", "16",
                ])
        finally:
            telemetry.set_registry(prev)
            flight_recorder.uninstall()
        files = crash_files(tel)
        assert len(files) == 1
        dump = json.load(open(files[0]))
        assert dump["exception"]["message"] == "disk on fire"
        assert any(e["event"] == "solve" for e in dump["events"])


class TestHooks:
    def test_install_uninstall_restores_hooks(self, tmp_path):
        prev_hook = sys.excepthook
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        rec = flight_recorder.install(str(tmp_path))
        try:
            assert sys.excepthook != prev_hook
            assert signal.getsignal(signal.SIGTERM) == rec._on_signal
            assert flight_recorder.active_recorder() is rec
            # Re-install re-points the directory, same recorder.
            assert flight_recorder.install("/elsewhere") is rec
            assert rec.directory == "/elsewhere"
        finally:
            flight_recorder.uninstall()
        assert sys.excepthook is prev_hook
        assert signal.getsignal(signal.SIGTERM) == prev_term
        assert signal.getsignal(signal.SIGINT) == prev_int
        assert flight_recorder.active_recorder() is None

    def test_sigterm_dumps_and_chains_previous_handler(self, tmp_path):
        """SIGTERM: dump first, then hand the signal to the previous
        owner (here a benign handler so the test survives)."""
        hits = []
        prev = signal.signal(
            signal.SIGTERM, lambda s, f: hits.append(s)
        )
        try:
            with telemetry.use(MetricsRegistry()):
                rec = FlightRecorder(str(tmp_path)).install()
                try:
                    signal.raise_signal(signal.SIGTERM)
                finally:
                    rec.uninstall()
        finally:
            signal.signal(signal.SIGTERM, prev)
        files = crash_files(tmp_path)
        assert len(files) == 1
        assert json.load(open(files[0]))["reason"] == "sigterm"
        assert hits == [signal.SIGTERM]  # previous owner still ran


class TestUnhealthyProbeTrigger:
    def test_unhealthy_probe_verdict_dumps(self, tmp_path, monkeypatch):
        from kafka_tpu.telemetry import health

        # Force both probe rounds off-band without waiting for a retry.
        monkeypatch.setattr(health, "HEALTHY_HOST_MS", -1.0)
        with telemetry.use(MetricsRegistry()):
            rec = FlightRecorder(str(tmp_path))
            monkeypatch.setattr(
                flight_recorder, "_active", rec
            )
            verdict = health.probe_health(retry_wait_s=0.0)
        assert verdict["unhealthy"]
        files = crash_files(tmp_path)
        assert len(files) == 1
        dump = json.load(open(files[0]))
        assert dump["reason"] == "unhealthy_probe"
        probe_events = [
            e for e in dump["events"] if e["event"] == "health_probe"
        ]
        assert probe_events and probe_events[-1]["unhealthy"]
        assert "kafka_health_probe_host_ms" in dump["metrics"]
