"""Fault-tolerance layer (ISSUE 6): retry/backoff policy, failure
classification, deterministic fault injection, and the graceful
degradation paths wired through prefetch, scheduler and checkpoint.

The four chaos acceptance tests:

(a) a transient read failure recovers via retry with BIT-IDENTICAL
    outputs vs the fault-free run;
(b) a retry-exhausted transient date degrades to predict-only and the
    run completes with the counter/event recorded;
(c) a poison chunk is quarantined with a ``.failed`` marker and the
    surviving chunks all complete;
(d) a truncated newest checkpoint falls back to the previous intact one.

Plus the end-to-end ``KAFKA_TPU_FAULTS``-scripted chaos run of
``run_synthetic`` combining (a)+(b)+(c) with a partial-success exit.
"""

import datetime
import json
import os
import time

import numpy as np
import pytest

from kafka_tpu import telemetry
from kafka_tpu.engine import Checkpointer, KalmanFilter
from kafka_tpu.engine.prefetch import ObservationPrefetcher
from kafka_tpu.engine.state import make_pixel_gather
from kafka_tpu.io.tiling import get_chunks
from kafka_tpu.resilience import (
    EXIT_PARTIAL_SUCCESS,
    FATAL,
    POISON,
    TRANSIENT,
    Deadline,
    DeadlineExceeded,
    DegradedDateError,
    RetryPolicy,
    classify_failure,
    faults,
)
from kafka_tpu.shard.scheduler import (
    failed_marker_path,
    marker_path,
    pending_chunks,
    assign_chunks,
    run_chunks,
)


def day(i):
    return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)


#: zero-wait deterministic policies for tests.
FAST2 = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)
FAST3 = RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# policy unit tests
# ---------------------------------------------------------------------------

class TestClassification:
    def test_heuristics(self):
        assert classify_failure(IOError("x")) == TRANSIENT
        assert classify_failure(TimeoutError()) == TRANSIENT
        assert classify_failure(ConnectionResetError()) == TRANSIENT
        assert classify_failure(ValueError("bad shape")) == POISON
        assert classify_failure(RuntimeError("?")) == POISON
        assert classify_failure(MemoryError()) == FATAL
        assert classify_failure(KeyboardInterrupt()) == FATAL

    def test_explicit_attribute_wins(self):
        exc = RuntimeError("flaky endpoint")
        exc.kafka_failure_class = TRANSIENT
        assert classify_failure(exc) == TRANSIENT

    def test_injected_fault_carries_class(self):
        f = faults.InjectedFault("a.b", 3, POISON)
        assert classify_failure(f) == POISON

    def test_deadline_exceeded_is_poison(self):
        assert classify_failure(DeadlineExceeded("late")) == POISON


class TestRetryPolicy:
    def test_deterministic_schedule(self):
        p = RetryPolicy(max_attempts=4, base_delay=0.5, multiplier=2.0,
                        max_delay=1.5, jitter=0.0)
        assert p.schedule() == [0.5, 1.0, 1.5]

    def test_retries_transient_then_succeeds(self):
        slept, calls = [], []
        p = RetryPolicy(max_attempts=3, base_delay=0.25, multiplier=2.0,
                        jitter=0.0, sleep=slept.append)

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("weather")
            return "ok"

        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            assert p.call(fn, site="t.site") == "ok"
            assert reg.value("kafka_resilience_retries_total",
                             site="t.site") == 2
            assert [e["event"] for e in reg.events] == ["retry", "retry"]
        assert slept == [0.25, 0.5]

    def test_poison_never_retried(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("deterministic")

        with telemetry.use(telemetry.MetricsRegistry()):
            with pytest.raises(ValueError):
                FAST3.call(fn)
        assert len(calls) == 1

    def test_exhaustion_reraises_original(self):
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            with pytest.raises(OSError, match="persistent"):
                FAST2.call(lambda: (_ for _ in ()).throw(
                    OSError("persistent")), site="t.x")
            assert [e["event"] for e in reg.events] == \
                ["retry", "retry_exhausted"]

    def test_deadline(self):
        d = Deadline(30.0)
        assert not d.expired and d.remaining() > 0
        d = Deadline(0.0)
        time.sleep(0.001)
        with pytest.raises(DeadlineExceeded, match="deadline"):
            d.check("probe")


# ---------------------------------------------------------------------------
# fault-injection registry
# ---------------------------------------------------------------------------

class TestFaults:
    def test_nth_call_and_counting(self):
        faults.script("a.b", "2")
        faults.fault_point("a.b")
        with pytest.raises(faults.InjectedFault, match="call #2"):
            faults.fault_point("a.b")
        faults.fault_point("a.b")  # only the 2nd call was scripted
        assert faults.call_count("a.b") == 3

    def test_ranges_and_classes(self):
        faults.script("s", "2-3", POISON)
        faults.fault_point("s")
        for _ in range(2):
            with pytest.raises(faults.InjectedFault) as ei:
                faults.fault_point("s")
            assert ei.value.kafka_failure_class == POISON
        faults.fault_point("s")  # call 4: clear again

    def test_open_ended_and_star(self):
        faults.script("t", "3+")
        faults.fault_point("t")
        faults.fault_point("t")
        for _ in range(5):
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("t")

    def test_env_spec_round_trip(self):
        n = faults.install_from_env(
            {"KAFKA_TPU_FAULTS":
             "prefetch.read_date@2;scheduler.run_one@3:poison"}
        )
        assert n == 2
        faults.fault_point("prefetch.read_date")
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("prefetch.read_date")
        assert faults.install_from_env({}) == 0

    def test_bad_specs_rejected(self):
        with pytest.raises(ValueError, match="expected"):
            faults.parse_spec("no-at-sign")
        with pytest.raises(ValueError, match="class"):
            faults.parse_spec("a.b@1:nuclear")

    def test_inactive_registry_is_free(self):
        # Nothing armed: fault points neither raise nor count.
        faults.fault_point("x")
        assert faults.call_count("x") == 0

    def test_fired_fault_lands_in_telemetry(self):
        faults.script("y", "1")
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("y", context="hello")
            assert reg.value("kafka_resilience_faults_injected_total",
                             site="y") == 1
            assert reg.events[-1]["event"] == "fault_injected"


# ---------------------------------------------------------------------------
# prefetch: retry, degradation, watchdog
# ---------------------------------------------------------------------------

class CountingSource:
    """The prefetch worker itself fires the ``prefetch.read_date``
    fault point (one call per attempt) — the source stays clean."""

    def __init__(self, dates):
        self.dates = list(dates)

    def get_observations(self, date, gather):
        return ("obs", date)


class TestPrefetchResilience:
    def _pf(self, dates, **kw):
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        return ObservationPrefetcher(
            CountingSource(dates), gather, dates, depth=2, **kw
        )

    def test_transient_read_recovers_via_retry(self):
        dates = [day(i) for i in range(4)]
        faults.script("prefetch.read_date", "2")
        pf = self._pf(dates, retry_policy=FAST2)
        try:
            for d in dates:
                assert pf.get(d) == ("obs", d)
        finally:
            pf.close()

    def test_exhausted_transient_degrades_and_continues(self):
        dates = [day(i) for i in range(4)]
        faults.script("prefetch.read_date", "2-3")  # date 1, both tries
        pf = self._pf(dates, retry_policy=FAST2)
        try:
            assert pf.get(dates[0]) == ("obs", dates[0])
            with pytest.raises(DegradedDateError) as ei:
                pf.get(dates[1])
            assert ei.value.date == dates[1]
            # Later dates still arrive: degraded does not stop claims.
            assert pf.get(dates[2]) == ("obs", dates[2])
            assert pf.get(dates[3]) == ("obs", dates[3])
        finally:
            pf.close()

    def test_poison_read_stays_fail_fast(self):
        dates = [day(i) for i in range(3)]
        faults.script("prefetch.read_date", "2", POISON)
        pf = self._pf(dates, retry_policy=FAST3)
        try:
            pf.get(dates[0])
            with pytest.raises(faults.InjectedFault):
                pf.get(dates[1])
        finally:
            pf.close()

    def test_dead_workers_watchdog_instead_of_wedge(self):
        dates = [day(0)]
        pf = self._pf(dates)
        try:
            pf.get(day(0))
            for t in pf._threads:
                t.join(timeout=5.0)
            # All workers exited, nothing will ever deliver day(1):
            # the old wait loop spun forever here.
            with pytest.raises(RuntimeError, match="workers died"):
                pf.get(day(1))
        finally:
            pf.close()


# ---------------------------------------------------------------------------
# engine: chaos (a) retry-recovery bit-identical, (b) degraded dates
# ---------------------------------------------------------------------------

def _engine_run(read_policy=None, max_degraded=8, exclude=(),
                prefetch_depth=2):
    import jax.numpy as jnp

    from kafka_tpu.core.propagators import PixelPrior
    from kafka_tpu.engine import FixedGaussianPrior
    from kafka_tpu.obsops import IdentityOperator
    from kafka_tpu.testing import MemoryOutput, SyntheticObservations

    rng = np.random.default_rng(3)
    mask = np.ones((6, 6), bool)
    p = 2
    op = IdentityOperator(n_params=p, obs_indices=(0, 1))
    truth = rng.uniform(0.3, 0.7, mask.shape + (p,)).astype(np.float32)
    obs = SyntheticObservations(
        dates=[day(i) for i in range(1, 7) if i not in exclude],
        operator=op,
        truth_fn=lambda date: truth,
        sigma=0.02,
        seed=5,
    )
    out = MemoryOutput()
    mean = np.full((p,), 0.5, np.float32)
    cov = np.diag(np.full((p,), 0.25)).astype(np.float32)
    prior = FixedGaussianPrior(
        PixelPrior(
            mean=jnp.asarray(mean), cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        ),
        ("a", "b"),
    )

    class PlainSource:
        """Thin wrapper: the engine/prefetcher fire the
        ``prefetch.read_date`` fault point (one call per attempt)."""

        dates = obs.dates

        def get_observations(self, date, gather):
            return obs.get_observations(date, gather)

    kf = KalmanFilter(
        PlainSource(), out, mask, ("a", "b"),
        state_propagation=None, prior=prior, pad_multiple=16,
        prefetch_depth=prefetch_depth,
        read_retry_policy=read_policy or FAST2,
        max_degraded_dates=max_degraded,
    )
    kf.set_trajectory_model()
    kf.set_trajectory_uncertainty(np.zeros(p, np.float32))
    x0, p_inv0 = prior.process_prior(None, kf.gather)
    grid = [day(0), day(3), day(6)]
    x_a, _, p_inv_a = kf.run(grid, x0, None, p_inv0)
    return np.asarray(x_a), np.asarray(p_inv_a), kf


class TestEngineDegradation:
    def test_chaos_a_transient_retry_bit_identical(self):
        """One transient failure on the 2nd read, recovered by retry:
        results must equal the fault-free run EXACTLY."""
        x_ref, pinv_ref, _ = _engine_run()
        faults.script("prefetch.read_date", "2")
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            x, pinv, _ = _engine_run()
            assert reg.value("kafka_resilience_retries_total",
                             site="prefetch.read_date") == 1
            assert reg.value("kafka_engine_dates_degraded_total") is None
        np.testing.assert_array_equal(x_ref, x)
        np.testing.assert_array_equal(pinv_ref, pinv)

    def test_chaos_b_exhausted_date_degrades_to_predict_only(self):
        """Retries exhausted on one date: the run completes, the date is
        consumed as a missing observation (results identical to a run
        that never had it), counter + event recorded."""
        # calls: 1 -> day1; 2,3 -> day2 twice (attempts of FAST2).
        faults.script("prefetch.read_date", "2-3")
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            x, pinv, kf = _engine_run()
            assert reg.value("kafka_engine_dates_degraded_total") == 1
            kinds = [e["event"] for e in reg.events]
            assert "date_degraded" in kinds and "retry_exhausted" in kinds
            degraded = [e for e in reg.events
                        if e["event"] == "date_degraded"][0]
            assert "2021-03-03" in degraded["date"]
        # The degraded date is absent from the assimilation log (the
        # fault-free run assimilates 5 dates, day 2..6)...
        assert len(kf.diagnostics_log) == 4
        assert day(2) not in [d["date"] for d in kf.diagnostics_log]
        # ...and the arithmetic equals the run that never saw day 2.
        x_ref, pinv_ref, _ = _engine_run(exclude=(2,))
        np.testing.assert_array_equal(x_ref, x)
        np.testing.assert_array_equal(pinv_ref, pinv)

    def test_degraded_budget_aborts(self):
        faults.script("prefetch.read_date", "*")
        with telemetry.use(telemetry.MetricsRegistry()):
            with pytest.raises(RuntimeError, match="max_degraded_dates"):
                _engine_run(max_degraded=0)

    def test_synchronous_path_degrades_too(self):
        """prefetch_depth=0 (reference-style reads in the loop) shares
        the retry/degradation semantics."""
        faults.script("prefetch.read_date", "2-3")
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            x, pinv, _ = _engine_run(prefetch_depth=0)
            assert reg.value("kafka_engine_dates_degraded_total") == 1
        x_ref, pinv_ref, _ = _engine_run(exclude=(2,))
        np.testing.assert_array_equal(x_ref, x)
        np.testing.assert_array_equal(pinv_ref, pinv)


# ---------------------------------------------------------------------------
# scheduler: chaos (c) quarantine + retry + deadline
# ---------------------------------------------------------------------------

class TestSchedulerResilience:
    def _chunks(self, n=4):
        return list(get_chunks(256, 64 * n, (256, 64)))[:n]

    def test_chaos_c_poison_chunk_quarantined(self, tmp_path):
        """The poison chunk writes a .failed marker; every surviving
        chunk completes; the failed count is returned; a restart skips
        the quarantined chunk instead of re-wedging on it."""
        chunks = self._chunks(4)
        outdir = str(tmp_path)
        ran = []

        def run_one(chunk, prefix):
            if prefix == "0003":
                raise ValueError("poison pixel block")
            ran.append(prefix)

        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_chunks(
                chunks, run_one, outdir, num_processes=1,
                process_index=0, retry_policy=FAST2, quarantine=True,
            )
            assert stats["run"] == 3 and stats["failed"] == 1
            assert reg.value("kafka_shard_chunks_failed_total") == 1
            kinds = [e["event"] for e in reg.events]
            assert kinds.count("chunk_quarantined") == 1
        assert sorted(ran) == ["0001", "0002", "0004"]
        fm = failed_marker_path(outdir, "0003")
        assert os.path.exists(fm)
        payload = json.load(open(fm))
        assert payload["failure_class"] == POISON
        assert "poison pixel block" in payload["error"]
        # Poison is never retried: exactly one attempt happened.
        # Restart: the quarantined chunk is skipped, nothing re-runs.
        stats2 = run_chunks(chunks, run_one, outdir, num_processes=1,
                            process_index=0, quarantine=True)
        assert stats2["run"] == 0 and stats2["skipped"] == 4
        assert pending_chunks(
            assign_chunks(chunks, 1), outdir, 0) == []

    def test_transient_chunk_retry_succeeds(self, tmp_path):
        chunks = self._chunks(3)
        faults.script("scheduler.run_one", "2")  # 2nd chunk, 1st try
        done = []
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_chunks(
                chunks, lambda c, p: done.append(p), str(tmp_path),
                num_processes=1, process_index=0,
                retry_policy=FAST2, quarantine=True,
            )
            assert reg.value("kafka_resilience_retries_total",
                             site="scheduler.run_one") == 1
        assert stats["run"] == 3 and stats["failed"] == 0
        assert len(done) == 3
        assert not os.path.exists(failed_marker_path(str(tmp_path),
                                                     "0002"))

    def test_deadline_exceeded_quarantines(self, tmp_path):
        chunks = self._chunks(1)

        def slow(chunk, prefix):
            time.sleep(0.05)

        stats = run_chunks(
            chunks, slow, str(tmp_path), num_processes=1,
            process_index=0, quarantine=True, chunk_deadline_s=0.01,
        )
        assert stats["failed"] == 1
        payload = json.load(
            open(failed_marker_path(str(tmp_path), "0001")))
        assert "deadline" in payload["error"]

    def test_fatal_always_propagates(self, tmp_path):
        chunks = self._chunks(2)

        def run_one(chunk, prefix):
            raise KeyboardInterrupt

        with pytest.raises(KeyboardInterrupt):
            run_chunks(chunks, run_one, str(tmp_path), num_processes=1,
                       process_index=0, retry_policy=FAST3,
                       quarantine=True)

    def test_default_stays_fail_fast(self, tmp_path):
        chunks = self._chunks(2)
        with pytest.raises(ValueError, match="boom"):
            run_chunks(
                chunks,
                lambda c, p: (_ for _ in ()).throw(ValueError("boom")),
                str(tmp_path), num_processes=1, process_index=0,
            )

    def test_done_marker_written_atomically(self, tmp_path):
        chunks = self._chunks(1)
        run_chunks(chunks, lambda c, p: None, str(tmp_path),
                   num_processes=1, process_index=0)
        mp = marker_path(str(tmp_path), "0001")
        assert os.path.exists(mp) and not os.path.exists(mp + ".tmp")
        assert "finished" in json.load(open(mp))


# ---------------------------------------------------------------------------
# checkpoint: chaos (d) truncated newest falls back
# ---------------------------------------------------------------------------

class TestCheckpointResilience:
    def _save_two(self, folder, n_pix=8, p=2):
        ck = Checkpointer(str(folder))
        rng = np.random.default_rng(0)
        states = {}
        for i, ts in enumerate([day(1), day(2)]):
            x = rng.normal(size=(n_pix, p)).astype(np.float32)
            pinv = np.stack([np.eye(p, dtype=np.float32) * (2 + i)] * n_pix)
            ck.save(ts, x, pinv)
            states[ts] = x
        return ck, states

    def test_atomic_save_leaves_no_tmp(self, tmp_path):
        ck, _ = self._save_two(tmp_path)
        assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
        assert len(ck.list_checkpoints()) == 2

    def test_chaos_d_truncated_newest_falls_back(self, tmp_path):
        ck, states = self._save_two(tmp_path)
        newest = ck.list_checkpoints()[-1][1][0]
        with open(newest, "r+b") as f:
            f.truncate(40)  # torn write / partial flush
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            ts, x, pinv = ck.load_latest()
            assert reg.value("kafka_checkpoint_unreadable_total") == 1
            assert reg.events[-1]["event"] == "checkpoint_unreadable"
        assert ts == day(1)
        np.testing.assert_array_equal(x, states[day(1)])
        assert pinv is not None and pinv[0, 0, 0] == 2.0

    def test_empty_newest_falls_back(self, tmp_path):
        ck, _ = self._save_two(tmp_path)
        newest = ck.list_checkpoints()[-1][1][0]
        open(newest, "wb").close()
        with telemetry.use(telemetry.MetricsRegistry()):
            ts, _, _ = ck.load_latest()
        assert ts == day(1)

    def test_all_unreadable_returns_none(self, tmp_path):
        ck, _ = self._save_two(tmp_path)
        for _, paths in ck.list_checkpoints():
            for q in paths:
                open(q, "wb").close()
        with telemetry.use(telemetry.MetricsRegistry()):
            assert ck.load_latest() is None

    def test_resume_time_grid_uses_fallback(self, tmp_path):
        ck, states = self._save_two(tmp_path)
        newest = ck.list_checkpoints()[-1][1][0]
        with open(newest, "r+b") as f:
            f.truncate(10)
        with telemetry.use(telemetry.MetricsRegistry()):
            grid, seed = ck.resume_time_grid([day(i) for i in range(5)])
        assert grid[0] == day(1) and seed is not None

    def test_injected_save_fault_leaves_previous_intact(self, tmp_path):
        ck = Checkpointer(str(tmp_path))
        x = np.zeros((4, 2), np.float32)
        pinv = np.stack([np.eye(2, dtype=np.float32)] * 4)
        faults.script("checkpoint.save", "2")  # armed before call 1
        ck.save(day(1), x, pinv)
        with pytest.raises(faults.InjectedFault):
            ck.save(day(2), x, pinv)
        ckpts = ck.list_checkpoints()
        assert [ts for ts, _ in ckpts] == [day(1)]
        assert ck.load_latest()[0] == day(1)


# ---------------------------------------------------------------------------
# end-to-end: the KAFKA_TPU_FAULTS-scripted chaos run of run_synthetic
# ---------------------------------------------------------------------------

#: transient read failure recovered by retry in chunk 0001 (call 2 of
#: prefetch.read_date; retry = call 3), a date in chunk 0002 failing
#: both attempts (calls 6-7 -> degraded, predict-only), and chunk 0003
#: poisoned at the scheduler (3rd run_one call, never retried).
CHAOS_SPEC = ("prefetch.read_date@2;prefetch.read_date@6-7;"
              "scheduler.run_one@3:poison")


def _run_synthetic_chunked(outdir, tel_dir, mask_tif):
    from kafka_tpu.cli.run_synthetic import main

    return main([
        "--operator", "identity", "--outdir", str(outdir),
        "--mask", str(mask_tif), "--days", "8", "--step", "4",
        "--obs-every", "2", "--chunk-size", "16",
        "--chunk-attempts", "2", "--read-attempts", "2",
        "--retry-delay-s", "0.01",
        "--telemetry-dir", str(tel_dir),
    ])


class TestSyntheticChaosRun:
    def test_chaos_run_partial_success_and_bit_identical_survivors(
            self, tmp_path, monkeypatch):
        from kafka_tpu.io import read_geotiff, write_geotiff
        from kafka_tpu.testing.fixtures import DEFAULT_GEO

        mask_tif = tmp_path / "mask.tif"
        write_geotiff(str(mask_tif), np.ones((32, 32), np.uint8),
                      geo=DEFAULT_GEO)

        # Fault-free reference run.
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        faults.reset()
        ref = _run_synthetic_chunked(
            tmp_path / "ref", tmp_path / "tel_ref", mask_tif)
        assert ref["failed"] == 0 and ref["chunks_run"] == 4

        # Scripted chaos run.
        monkeypatch.setenv(faults.ENV_VAR, CHAOS_SPEC)
        faults.reset()
        chaos = _run_synthetic_chunked(
            tmp_path / "chaos", tmp_path / "tel", mask_tif)

        # Partial success: the poison chunk quarantined, the run
        # completed, and the exit-code mapping signals it.
        assert chaos["failed"] == 1
        assert chaos["chunks_run"] == 3
        from kafka_tpu.cli import make_console
        assert make_console(lambda: chaos)() == EXIT_PARTIAL_SUCCESS
        assert EXIT_PARTIAL_SUCCESS == 75
        assert os.path.exists(
            failed_marker_path(str(tmp_path / "chaos"), "0003"))

        # Forensics: quarantine + degraded-date (and injection/retry)
        # events are all in events.jsonl.
        events = [json.loads(line) for line in
                  open(tmp_path / "tel" / "events.jsonl")]
        kinds = [e["event"] for e in events]
        for expected in ("fault_injected", "retry", "retry_exhausted",
                         "date_degraded", "chunk_quarantined",
                         "run_done"):
            assert expected in kinds, f"missing {expected} in {kinds}"
        quarantined = [e for e in events
                       if e["event"] == "chunk_quarantined"][0]
        assert quarantined["prefix"] == "0003"

        # Unaffected chunks (0001 recovered via retry, 0004 untouched)
        # are BIT-IDENTICAL to the fault-free run.
        for prefix in ("0001", "0004"):
            ref_files = sorted(
                f for f in os.listdir(tmp_path / "ref")
                if f.endswith(".tif") and f"_{prefix}" in f
            )
            chaos_files = sorted(
                f for f in os.listdir(tmp_path / "chaos")
                if f.endswith(".tif") and f"_{prefix}" in f
            )
            assert ref_files == chaos_files and ref_files
            for fn in ref_files:
                a, _ = read_geotiff(str(tmp_path / "ref" / fn))
                b, _ = read_geotiff(str(tmp_path / "chaos" / fn))
                np.testing.assert_array_equal(a, b, err_msg=fn)
        # The degraded chunk still produced outputs (predict-only for
        # the failed date), and the quarantined one wrote no .done.
        assert any("_0002" in f for f in os.listdir(tmp_path / "chaos"))
        assert not os.path.exists(
            marker_path(str(tmp_path / "chaos"), "0003"))
