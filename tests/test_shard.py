"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4: the
multi-device test the reference entirely lacks)."""

import datetime
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_tpu.core.propagators import propagate_information_filter
from kafka_tpu.core.solvers import iterated_solve
from kafka_tpu.io.tiling import get_chunks
from kafka_tpu.testing.synthetic import make_tip_problem
from kafka_tpu.shard import (
    assign_chunks,
    make_pixel_mesh,
    make_sharded_step,
    pad_for_mesh,
    pending_chunks,
    run_chunks,
    shard_bands,
    shard_state,
)


_problem = make_tip_problem


def test_sharded_step_matches_single_device(eight_cpu_devices):
    """The fully-sharded advance+solve must agree with the unsharded path."""
    mesh = make_pixel_mesh(eight_cpu_devices)
    n_pix = pad_for_mesh(300, mesh, lane=8)
    assert n_pix % 8 == 0
    op, bands, x0, p_inv0 = _problem(n_pix)
    m = jnp.eye(7, dtype=jnp.float32)
    q = jnp.full((7,), 0.01, jnp.float32)
    opts = {"state_bounds": (
        jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
    )}

    step = make_sharded_step(
        op.linearize, mesh,
        state_propagator=propagate_information_filter,
        use_prior=False, solver_options=opts,
    )
    xs, ps = shard_state(mesh, x0, p_inv0)
    bs = shard_bands(mesh, bands)
    x_sh, p_inv_sh, diags_sh = step(bs, xs, ps, m, q, xs, ps, None)

    # Unsharded reference path: same propagator + solve on one device.
    x_f, _, p_f_inv = propagate_information_filter(x0, None, p_inv0, m, q)
    x_ref, p_inv_ref, diags_ref = iterated_solve(
        op.linearize, bands, x_f, p_f_inv, None, **opts
    )
    np.testing.assert_allclose(
        np.asarray(x_sh), np.asarray(x_ref), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(p_inv_sh), np.asarray(p_inv_ref), rtol=2e-4, atol=2e-2
    )
    assert int(diags_sh[2]) == int(diags_ref.n_iterations)


def test_sharded_step_is_actually_partitioned(eight_cpu_devices):
    mesh = make_pixel_mesh(eight_cpu_devices)
    n_pix = pad_for_mesh(100, mesh, lane=8)
    op, bands, x0, p_inv0 = _problem(n_pix)
    xs, ps = shard_state(mesh, x0, p_inv0)
    # Each device holds 1/8 of the pixel axis.
    assert len(xs.sharding.device_set) == 8
    shard_rows = {s.data.shape[0] for s in xs.addressable_shards}
    assert shard_rows == {n_pix // 8}


def test_pad_for_mesh(eight_cpu_devices):
    mesh = make_pixel_mesh(eight_cpu_devices)
    n = pad_for_mesh(1000, mesh)
    assert n >= 1000 and n % (8 * 128) == 0
    assert pad_for_mesh(1, mesh) == 8 * 128


def test_scheduler_round_robin_and_restart(tmp_path):
    chunks = list(get_chunks(512, 512, (128, 128)))  # 16 chunks
    a = assign_chunks(chunks, num_processes=4)
    owners = [x.owner for x in a]
    assert owners == [i % 4 for i in range(16)]
    # All processes together cover every chunk exactly once.
    outdir = str(tmp_path)
    ran = []

    def run_one(chunk, prefix):
        ran.append((chunk.chunk_no, prefix))

    for p in range(4):
        stats = run_chunks(chunks, run_one, outdir,
                           num_processes=4, process_index=p)
        assert stats["run"] == 4 and stats["skipped"] == 0
    assert len(ran) == 16
    assert len({c for c, _ in ran}) == 16
    # Restart: everything already marked done -> nothing reruns.
    stats = run_chunks(chunks, run_one, outdir,
                       num_processes=4, process_index=0)
    assert stats["run"] == 0 and stats["skipped"] == 4
    assert len(ran) == 16
    assert pending_chunks(assign_chunks(chunks, 4), outdir, 2) == []


def test_scheduler_crash_midway_reruns_exactly_missing(tmp_path):
    """Restart path: a chunk run that dies mid-way leaves NO .done marker,
    and ``pending_chunks`` on a replacement process re-runs exactly the
    missing chunks — no repeats, no gaps."""
    from kafka_tpu.shard.scheduler import marker_path

    chunks = list(get_chunks(512, 512, (128, 128)))  # 16 chunks
    outdir = str(tmp_path)
    assignments = assign_chunks(chunks, num_processes=2)
    mine = [a for a in assignments if a.owner == 0]
    die_at = mine[3].prefix  # crash on this process's 4th chunk
    ran = []

    def run_one_dying(chunk, prefix):
        if prefix == die_at:
            raise RuntimeError("synthetic mid-chunk crash")
        ran.append(prefix)

    with pytest.raises(RuntimeError, match="mid-chunk crash"):
        run_chunks(chunks, run_one_dying, outdir,
                   num_processes=2, process_index=0)
    # Completed chunks are durable, the crashed one left no marker.
    assert len(ran) == 3
    for p in ran:
        assert os.path.exists(marker_path(outdir, p))
    assert not os.path.exists(marker_path(outdir, die_at))
    # A replacement process sees exactly the missing chunks, crashed one
    # included, in the deterministic assignment order.
    pending = pending_chunks(assign_chunks(chunks, 2), outdir, 0)
    assert [a.prefix for a in pending] == \
        [a.prefix for a in mine if a.prefix not in ran]
    assert die_at in {a.prefix for a in pending}
    # The rerun completes only the missing work; nothing repeats.
    rerun = []
    stats = run_chunks(chunks, lambda c, p: rerun.append(p), outdir,
                       num_processes=2, process_index=0)
    assert stats["skipped"] == 3 and stats["run"] == len(mine) - 3
    assert set(rerun).isdisjoint(ran)
    assert pending_chunks(assign_chunks(chunks, 2), outdir, 0) == []


def test_legacy_failed_marker_payloads_honored(tmp_path):
    """Regression (ISSUE 7): a ``.failed`` marker with a pre-PR-6
    payload (no failure_class) or an empty/unparseable body must still
    be honoured by ``pending_chunks``, the queue scan and ``run_chunks``
    — never crash, never re-run the quarantined chunk."""
    import json as _json

    from kafka_tpu.shard.queue import queue_status, scan_chunk
    from kafka_tpu.shard.scheduler import failed_marker_path, run_chunks

    chunks = list(get_chunks(512, 512, (128, 128)))[:4]
    outdir = str(tmp_path)
    # Pre-PR-6 payload: just a timestamp, no failure_class/error.
    with open(failed_marker_path(outdir, "0001"), "w") as f:
        _json.dump({"failed": 1234.5}, f)
    # Worst case: an empty file (torn write predating atomic markers).
    open(failed_marker_path(outdir, "0002"), "wb").close()
    assignments = assign_chunks(chunks, num_processes=1)
    pending = pending_chunks(assignments, outdir, 0)
    assert [a.prefix for a in pending] == ["0003", "0004"]
    assert scan_chunk(outdir, "0001").state == "failed"
    assert scan_chunk(outdir, "0002").state == "failed"
    ran = []
    stats = run_chunks(chunks, lambda c, p: ran.append(p), outdir,
                       num_processes=1, process_index=0)
    assert sorted(ran) == ["0003", "0004"]
    assert stats["skipped"] == 2
    status = queue_status(outdir)
    assert status["counts"]["failed"] == 2


def test_write_marker_tmp_names_are_unique(tmp_path):
    """Regression (ISSUE 7): the fixed ``path + '.tmp'`` name let two
    hosts racing on one marker interleave open/os.replace and commit a
    torn payload — tmp names now carry pid + a per-process counter."""
    from kafka_tpu.shard.scheduler import _tmp_name, _write_marker

    target = str(tmp_path / ".chunk_0001.done")
    names = {_tmp_name(target) for _ in range(16)}
    assert len(names) == 16
    assert all(f".tmp.{os.getpid()}." in n for n in names)
    _write_marker(target, {"finished": True})
    assert os.path.exists(target)
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_sweep_stale_tmp_removes_orphans(tmp_path):
    """A crash between open and os.replace leaks the tmp forever; the
    scheduler startup sweep removes old ones (recursively — checkpoint
    tmps included) and records an event per file, while a fresh tmp
    (a write in flight on another host) is left alone."""
    from kafka_tpu import telemetry
    from kafka_tpu.shard.scheduler import sweep_stale_tmp

    outdir = tmp_path
    (outdir / "ckpt").mkdir()
    legacy = outdir / ".chunk_0001.done.tmp"
    unique = outdir / f".chunk_0002.failed.tmp.{os.getpid()}.7"
    ckpt = outdir / "ckpt" / "state_20170101T000000.npz.tmp"
    fresh = outdir / ".chunk_0003.done.tmp"
    for p in (legacy, unique, ckpt, fresh):
        p.write_bytes(b"orphan")
    old = time.time() - 3600
    for p in (legacy, unique, ckpt):
        os.utime(p, (old, old))
    # A real output file must never be touched.
    keeper = outdir / "a_A2017184_0001.tif"
    keeper.write_bytes(b"data")
    with telemetry.use(telemetry.MetricsRegistry()) as reg:
        removed = sweep_stale_tmp(str(outdir), older_than_s=60.0)
        assert reg.value("kafka_scheduler_stale_tmp_removed_total") == 3
        events = [e for e in reg.events
                  if e["event"] == "stale_tmp_removed"]
        assert len(events) == 3
    assert len(removed) == 3
    assert not legacy.exists() and not unique.exists() \
        and not ckpt.exists()
    assert fresh.exists() and keeper.exists()


def test_scheduler_records_telemetry(tmp_path):
    """Chunk completion + wall-time land in the registry; an outlier chunk
    is flagged as a straggler (counter + event)."""
    import time as _time

    from kafka_tpu import telemetry

    chunks = list(get_chunks(512, 256, (128, 128)))  # 8 chunks
    # Stable ~10ms baseline so scheduler jitter can't fake a 3x outlier;
    # the last chunk 'hangs' at >3x the median.
    walls = iter([0.01] * 7 + [0.12])

    def run_one(chunk, prefix):
        _time.sleep(next(walls))

    with telemetry.use(telemetry.MetricsRegistry()) as reg:
        stats = run_chunks(chunks, run_one, str(tmp_path),
                           num_processes=1, process_index=0)
        assert stats["run"] == 8
        assert reg.value("kafka_shard_chunks_completed_total") == 8
        assert reg.value("kafka_shard_chunks_pending") == 0
        assert reg.value("kafka_shard_stragglers_total") == 1
        events = [e["event"] for e in reg.events]
        assert events.count("chunk_done") == 8
        assert events.count("straggler") == 1


def test_fused_scan_composes_with_sharding(eight_cpu_devices):
    """Temporal fusion under GSPMD: assimilate_windows_scan on arrays
    sharded over the pixel mesh must run multi-device and agree with the
    single-device fused program (fusion x sharding composition)."""
    from kafka_tpu.core.solvers import assimilate_windows_scan
    from kafka_tpu.core.types import BandBatch
    from kafka_tpu.shard import pixel_sharding, replicated

    mesh = make_pixel_mesh(eight_cpu_devices)
    n_pix = pad_for_mesh(200, mesh, lane=8)
    op, b1, x0, pi0 = _problem(n_pix, seed=0)
    _, b2, _, _ = _problem(n_pix, seed=1)
    m = jnp.eye(7, dtype=jnp.float32)
    q = jnp.full((7,), 0.01, jnp.float32)
    opts = {"state_bounds": (
        jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
    )}
    stacked = BandBatch(
        y=jnp.stack([b1.y, b2.y]),
        r_inv=jnp.stack([b1.r_inv, b2.r_inv]),
        mask=jnp.stack([b1.mask, b2.mask]),
    )

    # single device
    _, _, xs_ref, diag_ref, iters_ref, _, _, _ = assimilate_windows_scan(
        op.linearize, stacked, x0, pi0, None, m, q, None, None,
        propagate_information_filter, dict(opts), None,
    )

    # sharded: pixel axis is axis 2 of the stacked bands (K, B, n)
    band_sh = pixel_sharding(mesh, batch_axis=2, ndim=3)
    stacked_sh = BandBatch(
        y=jax.device_put(stacked.y, band_sh),
        r_inv=jax.device_put(stacked.r_inv, band_sh),
        mask=jax.device_put(stacked.mask, band_sh),
    )
    xs0, ps0 = shard_state(mesh, x0, pi0)
    x_fin, p_fin, xs_sh, diag_sh, iters_sh, _, _, _ = \
        assimilate_windows_scan(
        op.linearize, stacked_sh, xs0, ps0, None, m, q, None, None,
        propagate_information_filter, dict(opts), None,
    )
    assert len(x_fin.sharding.device_set) == len(eight_cpu_devices)
    np.testing.assert_array_equal(
        np.asarray(iters_sh), np.asarray(iters_ref)
    )
    np.testing.assert_allclose(
        np.asarray(xs_sh), np.asarray(xs_ref), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(diag_sh), np.asarray(diag_ref), rtol=5e-3, atol=5e-2
    )


def test_sharded_step_per_pixel_convergence(eight_cpu_devices):
    """per_pixel_convergence under GSPMD: the converged mask comes back
    sharded over the pixel axis and pixels behave as on one device."""
    mesh = make_pixel_mesh(eight_cpu_devices)
    n_pix = pad_for_mesh(200, mesh, lane=8)
    op, bands, x0, p_inv0 = _problem(n_pix)
    m = jnp.eye(7, dtype=jnp.float32)
    q = jnp.full((7,), 0.01, jnp.float32)
    opts = {
        "state_bounds": (
            jnp.asarray(op.state_bounds[0]),
            jnp.asarray(op.state_bounds[1]),
        ),
        "relaxation": 0.7,
        "per_pixel_convergence": True,
    }
    step = make_sharded_step(
        op.linearize, mesh,
        state_propagator=propagate_information_filter,
        use_prior=False, solver_options=opts, n_valid=n_pix,
    )
    xs, ps = shard_state(mesh, x0, p_inv0)
    bs = shard_bands(mesh, bands)
    x_a, p_inv_a, diags = step(bs, xs, ps, m, q, xs, ps, None)
    frozen = np.asarray(diags.converged_mask)
    assert frozen.shape == (n_pix,) and frozen.any()
    assert len(diags.converged_mask.sharding.device_set) == \
        len(eight_cpu_devices)
    assert np.isfinite(np.asarray(x_a)).all()
