"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4: the
multi-device test the reference entirely lacks)."""

import datetime
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_tpu.core.propagators import propagate_information_filter
from kafka_tpu.core.solvers import iterated_solve
from kafka_tpu.io.tiling import get_chunks
from kafka_tpu.testing.synthetic import make_tip_problem
from kafka_tpu.shard import (
    assign_chunks,
    make_pixel_mesh,
    make_sharded_step,
    pad_for_mesh,
    pending_chunks,
    run_chunks,
    shard_bands,
    shard_state,
)


_problem = make_tip_problem


def test_sharded_step_matches_single_device(eight_cpu_devices):
    """The fully-sharded advance+solve must agree with the unsharded path."""
    mesh = make_pixel_mesh(eight_cpu_devices)
    n_pix = pad_for_mesh(300, mesh, lane=8)
    assert n_pix % 8 == 0
    op, bands, x0, p_inv0 = _problem(n_pix)
    m = jnp.eye(7, dtype=jnp.float32)
    q = jnp.full((7,), 0.01, jnp.float32)
    opts = {"state_bounds": (
        jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
    )}

    step = make_sharded_step(
        op.linearize, mesh,
        state_propagator=propagate_information_filter,
        use_prior=False, solver_options=opts,
    )
    xs, ps = shard_state(mesh, x0, p_inv0)
    bs = shard_bands(mesh, bands)
    x_sh, p_inv_sh, diags_sh = step(bs, xs, ps, m, q, xs, ps, None)

    # Unsharded reference path: same propagator + solve on one device.
    x_f, _, p_f_inv = propagate_information_filter(x0, None, p_inv0, m, q)
    x_ref, p_inv_ref, diags_ref = iterated_solve(
        op.linearize, bands, x_f, p_f_inv, None, **opts
    )
    np.testing.assert_allclose(
        np.asarray(x_sh), np.asarray(x_ref), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(p_inv_sh), np.asarray(p_inv_ref), rtol=2e-4, atol=2e-2
    )
    assert int(diags_sh[2]) == int(diags_ref.n_iterations)


def test_sharded_step_is_actually_partitioned(eight_cpu_devices):
    mesh = make_pixel_mesh(eight_cpu_devices)
    n_pix = pad_for_mesh(100, mesh, lane=8)
    op, bands, x0, p_inv0 = _problem(n_pix)
    xs, ps = shard_state(mesh, x0, p_inv0)
    # Each device holds 1/8 of the pixel axis.
    assert len(xs.sharding.device_set) == 8
    shard_rows = {s.data.shape[0] for s in xs.addressable_shards}
    assert shard_rows == {n_pix // 8}


def test_pad_for_mesh(eight_cpu_devices):
    mesh = make_pixel_mesh(eight_cpu_devices)
    n = pad_for_mesh(1000, mesh)
    assert n >= 1000 and n % (8 * 128) == 0
    assert pad_for_mesh(1, mesh) == 8 * 128


def test_scheduler_round_robin_and_restart(tmp_path):
    chunks = list(get_chunks(512, 512, (128, 128)))  # 16 chunks
    a = assign_chunks(chunks, num_processes=4)
    owners = [x.owner for x in a]
    assert owners == [i % 4 for i in range(16)]
    # All processes together cover every chunk exactly once.
    outdir = str(tmp_path)
    ran = []

    def run_one(chunk, prefix):
        ran.append((chunk.chunk_no, prefix))

    for p in range(4):
        stats = run_chunks(chunks, run_one, outdir,
                           num_processes=4, process_index=p)
        assert stats["run"] == 4 and stats["skipped"] == 0
    assert len(ran) == 16
    assert len({c for c, _ in ran}) == 16
    # Restart: everything already marked done -> nothing reruns.
    stats = run_chunks(chunks, run_one, outdir,
                       num_processes=4, process_index=0)
    assert stats["run"] == 0 and stats["skipped"] == 4
    assert len(ran) == 16
    assert pending_chunks(assign_chunks(chunks, 4), outdir, 2) == []
