"""Self-healing multi-host chunk queue (ISSUE 7): lease-based claiming,
heartbeats, crash-reclaim, SIGTERM drain, and the chaos acceptance tests.

The three acceptance scenarios:

(a) SIGKILL one of two local worker processes mid-chunk: the survivor
    reclaims the expired lease, every chunk reaches ``.done``, the
    survivor exits 0, and every output GeoTIFF is identical to a
    fault-free single-worker run;
(b) ``scheduler.commit@1:transient`` via ``KAFKA_TPU_FAULTS``: the
    double-execution (at-least-once) path converges to identical bytes;
(c) SIGTERM drain: the worker finishes its current chunk, releases
    leases, exits cleanly; ``queue_status`` reports the rest pending and
    a fresh worker finishes the run.

All tier-1 / CPU.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from kafka_tpu import telemetry
from kafka_tpu.io.tiling import get_chunks
from kafka_tpu.resilience import POISON, RetryPolicy, faults
from kafka_tpu.shard.queue import (
    DONE,
    FAILED,
    LEASE_EXPIRED,
    LEASED,
    PENDING,
    _Heartbeat,
    _try_claim,
    lease_path,
    queue_status,
    read_marker,
    run_queue,
    scan_chunk,
    write_manifest,
)
from kafka_tpu.shard.scheduler import (
    failed_marker_path,
    mark_done,
    marker_path,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: zero-wait deterministic retry for tests.
FAST2 = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _chunks(n=4):
    return list(get_chunks(32 * n, 32, (32, 32)))[:n]


def _subprocess_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


# ---------------------------------------------------------------------------
# lease mechanics
# ---------------------------------------------------------------------------

class TestLease:
    def test_claim_is_exclusive(self, tmp_path):
        d = str(tmp_path)
        assert _try_claim(d, "0001", "w1", 30.0) is not None
        assert _try_claim(d, "0001", "w2", 30.0) is None
        lease = read_marker(lease_path(d, "0001"))
        assert lease["owner"] == "w1" and lease["requeues"] == 0
        assert lease["deadline"] > time.time()
        # No tmp litter from either the winner or the loser.
        assert not [f for f in os.listdir(d) if ".tmp" in f]

    def test_scan_states(self, tmp_path):
        d = str(tmp_path)
        assert scan_chunk(d, "0001").state == PENDING
        _try_claim(d, "0001", "w1", 30.0)
        assert scan_chunk(d, "0001").state == LEASED
        # Expired: deadline in the past.
        _try_claim(d, "0002", "w1", -1.0)
        assert scan_chunk(d, "0002").state == LEASE_EXPIRED
        mark_done(d, "0003")
        assert scan_chunk(d, "0003").state == DONE

    def test_done_wins_over_stale_lease(self, tmp_path):
        d = str(tmp_path)
        _try_claim(d, "0001", "w1", 30.0)
        mark_done(d, "0001")
        s = scan_chunk(d, "0001", cleanup=True)
        assert s.state == DONE
        # The stale lease was garbage-collected on sight.
        assert not os.path.exists(lease_path(d, "0001"))

    def test_corrupt_lease_counts_expired(self, tmp_path):
        d = str(tmp_path)
        with open(lease_path(d, "0001"), "wb") as f:
            f.write(b"\x00torn")
        assert scan_chunk(d, "0001").state == LEASE_EXPIRED
        # ...and is therefore reclaimable.
        lease = _try_claim(d, "0001", "w2", 30.0, requeues=1, reclaim=True)
        assert lease is not None and lease["owner"] == "w2"

    def test_reclaim_replaces_expired_lease(self, tmp_path):
        d = str(tmp_path)
        _try_claim(d, "0001", "dead", 0.0)
        lease = _try_claim(d, "0001", "w2", 30.0, requeues=1, reclaim=True)
        assert lease["owner"] == "w2" and lease["requeues"] == 1
        assert read_marker(lease_path(d, "0001"))["owner"] == "w2"

    def test_heartbeat_renews_and_detects_loss(self, tmp_path):
        d = str(tmp_path)
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            hb = _Heartbeat(d, "w1", 30.0, interval_s=1000.0)
            try:
                lease = _try_claim(d, "0001", "w1", 30.0)
                hb.watch(lease)
                before = read_marker(lease_path(d, "0001"))["deadline"]
                time.sleep(0.01)
                hb.beat()
                after = read_marker(lease_path(d, "0001"))["deadline"]
                assert after > before
                # Another worker steals the lease: the next beat must
                # notice, stop renewing, and record the takeover.
                os.unlink(lease_path(d, "0001"))
                _try_claim(d, "0001", "thief", 30.0)
                hb.beat()
                assert hb.lost.is_set()
                assert read_marker(
                    lease_path(d, "0001"))["owner"] == "thief"
                assert [e["event"] for e in reg.events] == ["lease_lost"]
            finally:
                hb.stop()

    def test_heartbeat_fault_is_survived(self, tmp_path):
        d = str(tmp_path)
        faults.script("scheduler.heartbeat", "1")
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            hb = _Heartbeat(d, "w1", 30.0, interval_s=1000.0)
            try:
                lease = _try_claim(d, "0001", "w1", 30.0)
                hb.watch(lease)
                hb.beat()  # injected failure — recorded, not raised
                kinds = [e["event"] for e in reg.events]
                assert "heartbeat_failed" in kinds
                hb.beat()  # next beat renews normally
                assert read_marker(
                    lease_path(d, "0001"))["owner"] == "w1"
            finally:
                hb.stop()


# ---------------------------------------------------------------------------
# run_queue
# ---------------------------------------------------------------------------

class TestRunQueue:
    def test_single_worker_completes_all(self, tmp_path):
        d = str(tmp_path)
        chunks = _chunks(4)
        ran = []
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_queue(chunks, lambda c, p: ran.append(p), d,
                              lease_ttl_s=5.0)
            assert reg.value("kafka_shard_chunks_completed_total") == 4
            kinds = [e["event"] for e in reg.events]
            assert kinds.count("chunk_claimed") == 4
            assert kinds.count("chunk_done") == 4
        assert stats["run"] == 4 and stats["failed"] == 0
        assert stats["reclaimed"] == 0 and stats["pending_at_exit"] == 0
        assert sorted(ran) == ["0001", "0002", "0003", "0004"]
        for p in ran:
            assert os.path.exists(marker_path(d, p))
            assert not os.path.exists(lease_path(d, p))

    def test_restart_skips_done_and_failed(self, tmp_path):
        d = str(tmp_path)
        chunks = _chunks(4)
        mark_done(d, "0001")
        from kafka_tpu.shard.scheduler import mark_failed

        mark_failed(d, "0002", {"failure_class": "poison"})
        ran = []
        stats = run_queue(chunks, lambda c, p: ran.append(p), d,
                          lease_ttl_s=5.0)
        assert sorted(ran) == ["0003", "0004"]
        assert stats["run"] == 2 and stats["skipped"] == 2

    def test_reclaims_dead_workers_lease(self, tmp_path):
        """A lease whose owner stopped heartbeating expires and is
        reclaimed: the chunk re-runs, the reclaim is counted and the
        per-chunk requeue count lands in telemetry."""
        d = str(tmp_path)
        chunks = _chunks(4)
        _try_claim(d, "0002", "deadhost:1", 0.1)
        time.sleep(0.15)
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_queue(chunks, lambda c, p: None, d,
                              lease_ttl_s=0.5, poll_interval_s=0.05)
            assert stats["run"] == 4 and stats["reclaimed"] == 1
            assert reg.value("kafka_scheduler_reclaims_total") == 1
            assert reg.value("kafka_scheduler_chunk_requeues_total",
                             prefix="0002") == 1
            reclaims = [e for e in reg.events
                        if e["event"] == "chunk_reclaimed"]
            assert len(reclaims) == 1
            assert reclaims[0]["prefix"] == "0002"
            assert reclaims[0]["prev_owner"] == "deadhost:1"
        assert os.path.exists(marker_path(d, "0002"))
        assert not os.path.exists(lease_path(d, "0002"))

    def test_waits_for_live_lease_then_reclaims(self, tmp_path):
        """A LIVE lease is respected (no premature steal); once the
        deadline passes without renewal the worker takes over."""
        d = str(tmp_path)
        chunks = _chunks(2)
        _try_claim(d, "0001", "slowhost:1", 0.4)
        t0 = time.time()
        stats = run_queue(chunks, lambda c, p: None, d,
                          lease_ttl_s=0.5, poll_interval_s=0.05)
        assert stats["run"] == 2 and stats["reclaimed"] == 1
        # It actually waited for the deadline instead of stealing.
        assert time.time() - t0 >= 0.3

    def test_poison_chunk_quarantined(self, tmp_path):
        d = str(tmp_path)
        chunks = _chunks(4)

        def run_one(chunk, prefix):
            if prefix == "0003":
                raise ValueError("poison pixel block")

        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_queue(chunks, run_one, d, lease_ttl_s=5.0,
                              retry_policy=FAST2, quarantine=True)
            assert stats["run"] == 3 and stats["failed"] == 1
            assert reg.value("kafka_shard_chunks_failed_total") == 1
            kinds = [e["event"] for e in reg.events]
            assert kinds.count("chunk_quarantined") == 1
        payload = json.load(open(failed_marker_path(d, "0003")))
        assert payload["failure_class"] == POISON
        assert not os.path.exists(lease_path(d, "0003"))
        # All hosts honour the marker: a second worker skips it.
        stats2 = run_queue(chunks, run_one, d, lease_ttl_s=5.0,
                           quarantine=True)
        assert stats2["run"] == 0 and stats2["skipped"] == 4

    def test_fail_fast_releases_lease(self, tmp_path):
        d = str(tmp_path)
        chunks = _chunks(2)

        def run_one(chunk, prefix):
            raise ValueError("boom")

        with pytest.raises(ValueError, match="boom"):
            run_queue(chunks, run_one, d, lease_ttl_s=30.0)
        # The dying worker released its lease on the way out, so a
        # replacement need not wait for TTL expiry.
        assert not [f for f in os.listdir(d) if f.endswith(".lease")]

    def test_commit_fault_double_executes_to_identical_bytes(
            self, tmp_path):
        """``scheduler.commit`` transient failure: the retry re-runs the
        whole chunk — at-least-once — and the second completion
        overwrites the first's outputs with identical bytes."""
        d = str(tmp_path)
        chunks = _chunks(3)
        runs = []

        def run_one(chunk, prefix):
            runs.append(prefix)
            # Deterministic per-chunk output, atomically overwritten on
            # re-execution (same contract as the GeoTIFF writers).
            with open(os.path.join(d, f"out_{prefix}.bin"), "wb") as f:
                f.write((prefix * 100).encode())

        faults.script("scheduler.commit", "1")
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_queue(chunks, run_one, d, lease_ttl_s=5.0,
                              retry_policy=FAST2, quarantine=True)
            assert reg.value("kafka_resilience_retries_total",
                             site="scheduler.run_one") == 1
        assert stats["run"] == 3 and stats["failed"] == 0
        # One chunk executed twice (the commit fault), others once.
        assert len(runs) == 4 and len(set(runs)) == 3
        doubled = [p for p in set(runs) if runs.count(p) == 2][0]
        data = open(os.path.join(d, f"out_{doubled}.bin"), "rb").read()
        assert data == (doubled * 100).encode()
        for p in ("0001", "0002", "0003"):
            assert os.path.exists(marker_path(d, p))

    def test_claim_fault_is_survivable(self, tmp_path):
        d = str(tmp_path)
        chunks = _chunks(2)
        faults.script("scheduler.claim", "1")
        stats = run_queue(chunks, lambda c, p: None, d, lease_ttl_s=5.0,
                          poll_interval_s=0.05)
        assert stats["run"] == 2 and stats["claim_errors"] == 1

    def test_max_requeues_quarantines_crash_looper(self, tmp_path):
        """A chunk that keeps killing its workers must not be reclaimed
        forever: past the requeue budget it is quarantined."""
        d = str(tmp_path)
        chunks = _chunks(2)
        # A lease that already burned 3 requeues, expired again.
        _try_claim(d, "0001", "deadhost:1", 0.0, requeues=3)
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_queue(chunks, lambda c, p: None, d,
                              lease_ttl_s=0.3, poll_interval_s=0.05,
                              quarantine=True, max_requeues=3)
            kinds = [e["event"] for e in reg.events]
            assert "chunk_quarantined" in kinds
        assert stats["failed"] == 1 and stats["run"] == 1
        payload = json.load(open(failed_marker_path(d, "0001")))
        assert "requeue budget" in payload["error"]

    def test_sigterm_drains_gracefully(self, tmp_path):
        """(c) first SIGTERM: finish the current chunk, commit it,
        release everything, exit cleanly; the remaining chunks stay
        pending for the next worker."""
        d = str(tmp_path)
        chunks = _chunks(4)
        ran = []

        def run_one(chunk, prefix):
            if not ran:
                os.kill(os.getpid(), signal.SIGTERM)
            ran.append(prefix)

        prev = signal.getsignal(signal.SIGTERM)
        with telemetry.use(telemetry.MetricsRegistry()) as reg:
            stats = run_queue(chunks, run_one, d, lease_ttl_s=5.0)
            assert "worker_drain" in [e["event"] for e in reg.events]
        # Handler chain restored after the drain.
        assert signal.getsignal(signal.SIGTERM) == prev
        assert stats["drained"] is True
        assert stats["run"] == 1 and len(ran) == 1
        # The drained worker's chunk committed; the rest are PENDING
        # with no leases held.
        status = queue_status(d)
        assert status["counts"]["done"] == 1
        assert status["counts"]["pending"] == 3
        assert status["counts"]["leased"] == 0
        # A fresh worker finishes the run.
        stats2 = run_queue(chunks, lambda c, p: ran.append(p), d,
                           lease_ttl_s=5.0)
        assert stats2["run"] == 3 and stats2["pending_at_exit"] == 0
        assert queue_status(d)["counts"]["done"] == 4


# ---------------------------------------------------------------------------
# queue_status + tools/queue_status.py
# ---------------------------------------------------------------------------

class TestQueueStatus:
    def _mixed_dir(self, tmp_path):
        d = str(tmp_path)
        chunks = _chunks(5)
        write_manifest(d, chunks)
        mark_done(d, "0001")
        from kafka_tpu.shard.scheduler import mark_failed

        mark_failed(d, "0002", {"failure_class": "poison"})
        _try_claim(d, "0003", "alive:1", 60.0)
        _try_claim(d, "0004", "dead:9", 0.0)
        return d

    def test_counts_and_ownership(self, tmp_path):
        d = self._mixed_dir(tmp_path)
        status = queue_status(d)
        assert status["manifest"] and status["n_chunks"] == 5
        assert status["counts"] == {
            PENDING: 1, LEASED: 1, LEASE_EXPIRED: 1, DONE: 1, FAILED: 1,
        }
        assert status["workers"]["alive:1"]["live"] == ["0003"]
        assert status["workers"]["dead:9"]["expired"] == ["0004"]
        assert status["chunks"]["0005"]["state"] == PENDING

    def test_no_manifest_falls_back_to_markers(self, tmp_path):
        d = str(tmp_path)
        mark_done(d, "0001")
        _try_claim(d, "0002", "w", 60.0)
        status = queue_status(d)
        assert not status["manifest"]
        assert status["n_chunks"] == 2
        assert status["counts"][DONE] == 1
        assert status["counts"][LEASED] == 1

    def test_status_is_read_only(self, tmp_path):
        d = str(tmp_path)
        _try_claim(d, "0001", "w1", 60.0)
        mark_done(d, "0001")  # stale lease next to .done
        queue_status(d)
        assert os.path.exists(lease_path(d, "0001"))  # NOT cleaned

    def test_cli_smoke(self, tmp_path, capsys):
        from tools.queue_status import main

        d = self._mixed_dir(tmp_path)
        assert main([d]) == 0
        out = capsys.readouterr().out
        assert "done            1" in out
        assert "alive:1" in out and "dead:9" in out
        assert main([d, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["counts"]["done"] == 1
        assert payload["n_chunks"] == 5

    def test_cli_missing_dir(self, tmp_path, capsys):
        from tools.queue_status import main

        assert main([str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------------------------
# end-to-end chaos acceptance: run_synthetic --queue
# ---------------------------------------------------------------------------

def _synthetic_args(outdir, tel_dir=None, extra=()):
    args = [
        "--operator", "identity", "--outdir", str(outdir),
        "--ny", "48", "--nx", "48", "--days", "8", "--step", "4",
        "--obs-every", "2", "--chunk-size", "16",
        "--retry-delay-s", "0.01", "--queue", "--num-workers", "1",
    ]
    if tel_dir is not None:
        args += ["--telemetry-dir", str(tel_dir)]
    return args + list(extra)


def _tif_map(outdir):
    return sorted(f for f in os.listdir(outdir) if f.endswith(".tif"))


def _assert_outputs_identical(ref_dir, got_dir):
    from kafka_tpu.io import read_geotiff

    ref_files = _tif_map(ref_dir)
    got_files = _tif_map(got_dir)
    assert ref_files == got_files and ref_files
    for fn in ref_files:
        a, _ = read_geotiff(os.path.join(str(ref_dir), fn))
        b, _ = read_geotiff(os.path.join(str(got_dir), fn))
        np.testing.assert_array_equal(a, b, err_msg=fn)


class TestSyntheticQueueChaos:
    def _reference_run(self, tmp_path, monkeypatch):
        """Fault-free single-worker queue run (in-process)."""
        from kafka_tpu.cli.run_synthetic import main

        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        faults.reset()
        ref = main(_synthetic_args(tmp_path / "ref"))
        assert ref["failed"] == 0 and ref["pending"] == 0
        assert ref["chunks_run"] == 9
        return ref

    def test_chaos_sigkill_worker_survivor_reclaims(
            self, tmp_path, monkeypatch):
        """(a) Two local worker processes; one is SIGKILLed mid-chunk.
        The survivor reclaims the expired lease, all chunks reach .done,
        the survivor exits 0, and every output GeoTIFF equals the
        fault-free single-worker run."""
        self._reference_run(tmp_path, monkeypatch)
        outdir = tmp_path / "chaos"
        cmd = [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
               *_synthetic_args(outdir, extra=["--lease-ttl-s", "1.0"])]
        env = _subprocess_env()
        env.pop(faults.ENV_VAR, None)

        # Empty-mask chunks commit in milliseconds; a lease on a
        # NON-empty chunk lives for the whole solve, so killing at that
        # sighting is reliably mid-chunk.
        from kafka_tpu.io.tiling import chunk_mask
        from kafka_tpu.testing.fixtures import make_pivot_mask

        mask = make_pivot_mask(48, 48)
        slow_leases = {
            f".chunk_{c.chunk_no:04x}.lease"
            for c in get_chunks(48, 48, (16, 16))
            if chunk_mask(mask, c).any()
        }
        assert slow_leases

        victim = subprocess.Popen(
            cmd, env=env, cwd=REPO_ROOT,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                if victim.poll() is not None:
                    pytest.fail(
                        f"victim exited rc={victim.returncode} before "
                        "it could be killed"
                    )
                names = set(
                    os.listdir(outdir) if os.path.isdir(outdir) else ()
                )
                if names & slow_leases:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("victim never claimed a non-empty chunk")
            victim.kill()
            victim.wait(timeout=30)
        finally:
            if victim.poll() is None:
                victim.kill()

        orphaned = [
            n for n in os.listdir(outdir) if n.endswith(".lease")
            and not os.path.exists(
                os.path.join(outdir, n.replace(".lease", ".done")))
        ]
        assert orphaned, "SIGKILL must strand the victim's lease"

        tel = tmp_path / "tel_survivor"
        survivor = subprocess.run(
            [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
             *_synthetic_args(outdir, tel_dir=tel,
                              extra=["--lease-ttl-s", "1.0"])],
            env=env, cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=600,
        )
        assert survivor.returncode == 0, survivor.stderr[-2000:]
        summary = json.loads(survivor.stdout.strip().splitlines()[-1])
        assert summary["failed"] == 0 and summary["pending"] == 0
        assert summary["reclaimed"] >= 1

        # Queue fully drained: 9/9 done, no leases left.
        status = queue_status(str(outdir))
        assert status["counts"]["done"] == 9
        assert status["counts"]["leased"] == 0
        assert status["counts"]["lease_expired"] == 0

        # The reclaim is in the survivor's forensic record.
        events = [json.loads(line)
                  for line in open(tel / "events.jsonl")]
        kinds = [e["event"] for e in events]
        assert "chunk_reclaimed" in kinds
        metrics = json.load(open(tel / "metrics.json"))
        series = metrics["kafka_scheduler_reclaims_total"]["series"]
        assert series and series[0]["value"] >= 1

        # At-least-once safety: outputs identical to the fault-free run
        # even though the killed worker half-ran (and the survivor
        # re-ran) some chunks.
        _assert_outputs_identical(tmp_path / "ref", outdir)

    def test_chaos_commit_fault_converges_bit_identical(
            self, tmp_path, monkeypatch):
        """(b) scheduler.commit@1:transient via KAFKA_TPU_FAULTS: the
        first chunk executes twice (at-least-once) and the final outputs
        are identical to the fault-free run."""
        from kafka_tpu.cli.run_synthetic import main

        self._reference_run(tmp_path, monkeypatch)
        monkeypatch.setenv(faults.ENV_VAR, "scheduler.commit@1:transient")
        faults.reset()
        tel = tmp_path / "tel_commit"
        chaos = main(_synthetic_args(tmp_path / "chaos", tel_dir=tel,
                                     extra=["--chunk-attempts", "2"]))
        assert chaos["failed"] == 0 and chaos["pending"] == 0
        assert chaos["chunks_run"] == 9
        events = [json.loads(line) for line in open(tel / "events.jsonl")]
        kinds = [e["event"] for e in events]
        assert "fault_injected" in kinds and "retry" in kinds
        injected = [e for e in events if e["event"] == "fault_injected"]
        assert injected[0]["site"] == "scheduler.commit"
        _assert_outputs_identical(tmp_path / "ref", tmp_path / "chaos")

    def test_chaos_sigterm_drain_subprocess(self, tmp_path, monkeypatch):
        """(c) SIGTERM mid-run: the worker drains (finishes its chunk,
        releases leases, exits 0), queue_status reports the remainder
        pending, and a fresh worker finishes the run."""
        from kafka_tpu.cli.run_synthetic import main

        outdir = tmp_path / "drain"
        env = _subprocess_env()
        env.pop(faults.ENV_VAR, None)
        worker = subprocess.Popen(
            [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
             *_synthetic_args(outdir)],
            env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                if worker.poll() is not None:
                    pytest.fail(
                        f"worker exited rc={worker.returncode} before "
                        "SIGTERM"
                    )
                names = (os.listdir(outdir)
                         if os.path.isdir(outdir) else [])
                if any(n.endswith(".lease") for n in names):
                    break
                time.sleep(0.02)
            else:
                pytest.fail("worker never claimed a lease")
            worker.send_signal(signal.SIGTERM)
            out, _ = worker.communicate(timeout=600)
        finally:
            if worker.poll() is None:
                worker.kill()
        # Clean exit, not a crash: drained with the current chunk done.
        assert worker.returncode == 0
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["drained"] is True
        assert summary["failed"] == 0
        assert summary["chunks_run"] >= 1
        assert summary["pending"] == 9 - summary["chunks_run"]

        status = queue_status(str(outdir))
        assert status["counts"]["leased"] == 0
        assert status["counts"]["lease_expired"] == 0
        assert status["counts"]["pending"] == summary["pending"]
        assert status["counts"]["done"] == summary["chunks_run"]

        # A fresh worker (in-process) finishes the run.
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        faults.reset()
        finish = main(_synthetic_args(outdir))
        assert finish["failed"] == 0 and finish["pending"] == 0
        assert queue_status(str(outdir))["counts"]["done"] == 9
