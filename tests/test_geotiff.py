"""GeoTIFF codec tests: round-trips, compression paths, geo tags, the
native C++ codec, and the output writer's reference-naming contract."""

import datetime
import os
import zlib

import numpy as np
import pytest

from kafka_tpu.engine.state import make_pixel_gather
from kafka_tpu.io import (
    Chunk,
    GeoInfo,
    GeoTIFFOutput,
    chunk_geotransform,
    chunk_mask,
    get_chunks,
    read_geotiff,
    write_geotiff,
)

RNG = np.random.default_rng(21)


class TestRoundTrip:
    @pytest.mark.parametrize("dtype", [np.float32, np.uint16, np.uint8,
                                       np.int32])
    def test_roundtrip_dtypes(self, tmp_path, dtype):
        if np.issubdtype(dtype, np.floating):
            arr = RNG.normal(size=(70, 53)).astype(dtype)
        else:
            arr = RNG.integers(0, 200, size=(70, 53)).astype(dtype)
        path = str(tmp_path / "t.tif")
        write_geotiff(path, arr)
        back, info = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)
        assert info.dtype == np.dtype(dtype)

    def test_roundtrip_uncompressed_and_predictor(self, tmp_path):
        arr = RNG.integers(0, 1000, size=(40, 40)).astype(np.uint16)
        p1 = str(tmp_path / "u.tif")
        write_geotiff(p1, arr, compress=False)
        back, info = read_geotiff(p1)
        np.testing.assert_array_equal(back, arr)
        assert info.compression == 1
        p2 = str(tmp_path / "p.tif")
        write_geotiff(p2, arr, predictor=2)
        back2, info2 = read_geotiff(p2)
        np.testing.assert_array_equal(back2, arr)
        assert info2.predictor == 2
        # predictor 2 is integer-only per the TIFF spec
        with pytest.raises(ValueError):
            write_geotiff(str(tmp_path / "f.tif"),
                          arr.astype(np.float32), predictor=2)

    def test_roundtrip_multiband(self, tmp_path):
        arr = RNG.normal(size=(33, 45, 3)).astype(np.float32)
        path = str(tmp_path / "mb.tif")
        write_geotiff(path, arr)
        back, info = read_geotiff(path)
        assert info.n_bands == 3
        np.testing.assert_array_equal(back, arr)

    def test_roundtrip_non_tile_aligned(self, tmp_path):
        arr = RNG.normal(size=(300, 513)).astype(np.float32)
        path = str(tmp_path / "big.tif")
        write_geotiff(path, arr, tile_size=256)
        back, _ = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)

    def test_geo_tags_roundtrip(self, tmp_path):
        arr = np.zeros((16, 16), np.float32)
        gt = (499980.0, 10.0, 0.0, 4400040.0, 0.0, -10.0)
        geo = GeoInfo(geotransform=gt, projection="WGS 84 / UTM zone 30N",
                      epsg=32630, nodata=-999.0)
        path = str(tmp_path / "geo.tif")
        write_geotiff(path, arr, geo)
        _, info = read_geotiff(path)
        np.testing.assert_allclose(info.geo.geotransform, gt)
        assert info.geo.epsg == 32630
        assert "UTM zone 30N" in info.geo.projection
        assert info.geo.nodata == -999.0

    def test_bigtiff_roundtrip(self, tmp_path):
        arr = RNG.normal(size=(64, 64)).astype(np.float32)
        path = str(tmp_path / "big8.tif")
        write_geotiff(path, arr, bigtiff=True)
        with open(path, "rb") as f:
            assert f.read(4)[2:4] == b"+\x00"  # magic 43
        back, _ = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)


class TestNativeCodec:
    def test_native_matches_zlib(self):
        from kafka_tpu.native import load_library

        lib = load_library()
        if lib is None:
            pytest.skip("native codec not built")
        blobs = [RNG.integers(0, 255, size=1000).astype(np.uint8).tobytes()
                 for _ in range(20)]
        comp = lib.deflate_many(blobs, 6)
        for c, b in zip(comp, blobs):
            assert zlib.decompress(c) == b
        decomp = lib.inflate_many([zlib.compress(b) for b in blobs], 1000)
        assert decomp == blobs


class TestOutputWriter:
    def test_reference_naming_and_content(self, tmp_path):
        mask = np.zeros((20, 20), bool)
        mask[5:15, 5:15] = True
        gather = make_pixel_gather(mask, pad_multiple=128)
        x = RNG.normal(size=(gather.n_pad, 2)).astype(np.float32)
        p_inv_diag = np.full((gather.n_pad, 2), 16.0, np.float32)
        out = GeoTIFFOutput(
            ["lai", "sm"], (0, 10, 0, 0, 0, -10), folder=str(tmp_path),
            prefix="0xa",
        )
        ts = datetime.datetime(2017, 7, 9)
        out.dump_data(ts, x, p_inv_diag, gather, ["lai", "sm"])
        # Reference naming: {param}_{A%Y%j}_{prefix}[_unc].tif
        # (observations.py:358-365)
        for param in ("lai", "sm"):
            mean_f = tmp_path / f"{param}_A2017190_0xa.tif"
            unc_f = tmp_path / f"{param}_A2017190_0xa_unc.tif"
            assert mean_f.exists() and unc_f.exists()
        lai, _ = read_geotiff(str(tmp_path / "lai_A2017190_0xa.tif"))
        assert lai.shape == mask.shape
        np.testing.assert_allclose(
            lai[mask], x[: gather.n_valid, 0], rtol=1e-6
        )
        assert np.all(lai[~mask] == 0)
        unc, _ = read_geotiff(str(tmp_path / "lai_A2017190_0xa_unc.tif"))
        np.testing.assert_allclose(unc[mask], 0.25, rtol=1e-6)

    def test_async_writer_flush(self, tmp_path):
        mask = np.ones((8, 8), bool)
        gather = make_pixel_gather(mask, pad_multiple=64)
        out = GeoTIFFOutput(
            ["a"], (0, 1, 0, 0, 0, -1), folder=str(tmp_path),
            async_writes=True,
        )
        for i in range(3):
            out.dump_data(
                datetime.datetime(2020, 1, 1 + i),
                np.full((gather.n_pad, 1), float(i), np.float32),
                None, gather, ["a"],
            )
        out.close()
        assert len(list(tmp_path.glob("*.tif"))) == 3


class TestChunks:
    def test_get_chunks_matches_reference_semantics(self):
        chunks = list(get_chunks(300, 200, (128, 128)))
        # column-major: X outer, Y inner (input_output/utils.py:20-40)
        assert [c.chunk_no for c in chunks] == [1, 2, 3, 4, 5, 6]
        assert chunks[0] == Chunk(0, 0, 128, 128, 1)
        assert chunks[1] == Chunk(0, 128, 128, 72, 2)
        assert chunks[-1] == Chunk(256, 128, 44, 72, 6)

    def test_chunk_mask_and_geotransform(self):
        mask = np.zeros((200, 300), bool)
        mask[130:150, 260:280] = True
        c = list(get_chunks(300, 200, (128, 128)))[-1]
        sub = chunk_mask(mask, c)
        assert sub.shape == (72, 44)
        assert sub.sum() == mask.sum()
        gt = chunk_geotransform((1000.0, 10, 0, 2000.0, 0, -10), c)
        assert gt == (1000.0 + 256 * 10, 10, 0, 2000.0 - 128 * 10, 0, -10)
