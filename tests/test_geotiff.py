"""GeoTIFF codec tests: round-trips, compression paths, geo tags, the
native C++ codec, and the output writer's reference-naming contract."""

import datetime
import os
import zlib

import numpy as np
import pytest

from kafka_tpu.engine.state import make_pixel_gather
from kafka_tpu.io import (
    Chunk,
    GeoInfo,
    GeoTIFFOutput,
    chunk_geotransform,
    chunk_mask,
    get_chunks,
    read_geotiff,
    write_geotiff,
)

RNG = np.random.default_rng(21)


class TestRoundTrip:
    @pytest.mark.parametrize("dtype", [np.float32, np.uint16, np.uint8,
                                       np.int32])
    def test_roundtrip_dtypes(self, tmp_path, dtype):
        if np.issubdtype(dtype, np.floating):
            arr = RNG.normal(size=(70, 53)).astype(dtype)
        else:
            arr = RNG.integers(0, 200, size=(70, 53)).astype(dtype)
        path = str(tmp_path / "t.tif")
        write_geotiff(path, arr)
        back, info = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)
        assert info.dtype == np.dtype(dtype)

    def test_roundtrip_uncompressed_and_predictor(self, tmp_path):
        arr = RNG.integers(0, 1000, size=(40, 40)).astype(np.uint16)
        p1 = str(tmp_path / "u.tif")
        write_geotiff(p1, arr, compress=False)
        back, info = read_geotiff(p1)
        np.testing.assert_array_equal(back, arr)
        assert info.compression == 1
        p2 = str(tmp_path / "p.tif")
        write_geotiff(p2, arr, predictor=2)
        back2, info2 = read_geotiff(p2)
        np.testing.assert_array_equal(back2, arr)
        assert info2.predictor == 2
        # predictor 2 is integer-only per the TIFF spec
        with pytest.raises(ValueError):
            write_geotiff(str(tmp_path / "f.tif"),
                          arr.astype(np.float32), predictor=2)

    def test_roundtrip_multiband(self, tmp_path):
        arr = RNG.normal(size=(33, 45, 3)).astype(np.float32)
        path = str(tmp_path / "mb.tif")
        write_geotiff(path, arr)
        back, info = read_geotiff(path)
        assert info.n_bands == 3
        np.testing.assert_array_equal(back, arr)

    def test_roundtrip_non_tile_aligned(self, tmp_path):
        arr = RNG.normal(size=(300, 513)).astype(np.float32)
        path = str(tmp_path / "big.tif")
        write_geotiff(path, arr, tile_size=256)
        back, _ = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)

    def test_geo_tags_roundtrip(self, tmp_path):
        arr = np.zeros((16, 16), np.float32)
        gt = (499980.0, 10.0, 0.0, 4400040.0, 0.0, -10.0)
        geo = GeoInfo(geotransform=gt, projection="WGS 84 / UTM zone 30N",
                      epsg=32630, nodata=-999.0)
        path = str(tmp_path / "geo.tif")
        write_geotiff(path, arr, geo)
        _, info = read_geotiff(path)
        np.testing.assert_allclose(info.geo.geotransform, gt)
        assert info.geo.epsg == 32630
        assert "UTM zone 30N" in info.geo.projection
        assert info.geo.nodata == -999.0

    def test_bigtiff_roundtrip(self, tmp_path):
        arr = RNG.normal(size=(64, 64)).astype(np.float32)
        path = str(tmp_path / "big8.tif")
        write_geotiff(path, arr, bigtiff=True)
        with open(path, "rb") as f:
            assert f.read(4)[2:4] == b"+\x00"  # magic 43
        back, _ = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)


class TestNativeCodec:
    def test_native_matches_zlib(self):
        from kafka_tpu.native import load_library

        lib = load_library()
        if lib is None:
            pytest.skip("native codec not built")
        blobs = [RNG.integers(0, 255, size=1000).astype(np.uint8).tobytes()
                 for _ in range(20)]
        comp = lib.deflate_many(blobs, 6)
        for c, b in zip(comp, blobs):
            assert zlib.decompress(c) == b
        decomp = lib.inflate_many([zlib.compress(b) for b in blobs], 1000)
        assert decomp == blobs


class TestOutputWriter:
    def test_reference_naming_and_content(self, tmp_path):
        mask = np.zeros((20, 20), bool)
        mask[5:15, 5:15] = True
        gather = make_pixel_gather(mask, pad_multiple=128)
        x = RNG.normal(size=(gather.n_pad, 2)).astype(np.float32)
        p_inv_diag = np.full((gather.n_pad, 2), 16.0, np.float32)
        out = GeoTIFFOutput(
            ["lai", "sm"], (0, 10, 0, 0, 0, -10), folder=str(tmp_path),
            prefix="0xa",
        )
        ts = datetime.datetime(2017, 7, 9)
        out.dump_data(ts, x, p_inv_diag, gather, ["lai", "sm"])
        # Reference naming: {param}_{A%Y%j}_{prefix}[_unc].tif
        # (observations.py:358-365)
        for param in ("lai", "sm"):
            mean_f = tmp_path / f"{param}_A2017190_0xa.tif"
            unc_f = tmp_path / f"{param}_A2017190_0xa_unc.tif"
            assert mean_f.exists() and unc_f.exists()
        lai, _ = read_geotiff(str(tmp_path / "lai_A2017190_0xa.tif"))
        assert lai.shape == mask.shape
        np.testing.assert_allclose(
            lai[mask], x[: gather.n_valid, 0], rtol=1e-6
        )
        assert np.all(lai[~mask] == 0)
        unc, _ = read_geotiff(str(tmp_path / "lai_A2017190_0xa_unc.tif"))
        np.testing.assert_allclose(unc[mask], 0.25, rtol=1e-6)

    def test_device_array_float16_wire(self, tmp_path):
        """The opt-in fast wire: float16 downcast, on-device sigma,
        unobserved pixels clamped to the float16 max (finite 'absurdly
        large sigma', still thresholdable — observations.py:393)."""
        import jax.numpy as jnp

        mask = np.ones((8, 16), bool)
        gather = make_pixel_gather(mask, pad_multiple=128)
        x = RNG.uniform(0.05, 2.0, (gather.n_pad, 2)).astype(np.float32)
        p_inv_diag = np.full((gather.n_pad, 2), 16.0, np.float32)
        p_inv_diag[3, :] = 0.0  # an unobserved pixel
        out = GeoTIFFOutput(
            ["lai", "sm"], (0, 10, 0, 0, 0, -10), folder=str(tmp_path),
            wire_dtype="float16",
        )
        ts = datetime.datetime(2019, 6, 1)
        out.dump_data(ts, jnp.asarray(x), jnp.asarray(p_inv_diag),
                      gather, ["lai", "sm"])
        mean, _ = read_geotiff(str(tmp_path / "lai_A2019152.tif"))
        np.testing.assert_allclose(
            mean[mask], x[: gather.n_valid, 0], rtol=1.5e-3
        )
        unc, _ = read_geotiff(str(tmp_path / "lai_A2019152_unc.tif"))
        expect = np.full(gather.n_valid, 0.25, np.float32)
        expect[3] = 65504.0  # clamped, finite, huge
        np.testing.assert_allclose(unc[mask], expect, rtol=1.5e-3)
        assert np.isfinite(unc[mask]).all()

    def test_default_wire_is_bit_exact_float32(self, tmp_path):
        """The DEFAULT wire must be float32/bit-exact, matching the
        reference's outputs without opt-in (round-2 advisor finding)."""
        import jax.numpy as jnp

        mask = np.ones((4, 8), bool)
        gather = make_pixel_gather(mask, pad_multiple=32)
        x = RNG.normal(size=(gather.n_pad, 1)).astype(np.float32)
        out = GeoTIFFOutput(
            ["a"], (0, 1, 0, 0, 0, -1), folder=str(tmp_path)
        )
        assert out.wire_dtype == "float32"
        out.dump_data(datetime.datetime(2019, 6, 3), jnp.asarray(x),
                      None, gather, ["a"])
        mean, _ = read_geotiff(str(tmp_path / "a_A2019154.tif"))
        np.testing.assert_array_equal(mean[mask], x[: gather.n_valid, 0])

    def test_device_array_float32_wire_exact(self, tmp_path):
        import jax.numpy as jnp

        mask = np.ones((4, 8), bool)
        gather = make_pixel_gather(mask, pad_multiple=32)
        x = RNG.normal(size=(gather.n_pad, 1)).astype(np.float32)
        out = GeoTIFFOutput(
            ["a"], (0, 1, 0, 0, 0, -1), folder=str(tmp_path),
            wire_dtype="float32",
        )
        out.dump_data(datetime.datetime(2019, 6, 2), jnp.asarray(x),
                      jnp.asarray(np.full((gather.n_pad, 1), 4.0,
                                          np.float32)),
                      gather, ["a"])
        mean, _ = read_geotiff(str(tmp_path / "a_A2019153.tif"))
        np.testing.assert_array_equal(mean[mask], x[: gather.n_valid, 0])
        unc, _ = read_geotiff(str(tmp_path / "a_A2019153_unc.tif"))
        np.testing.assert_allclose(unc[mask], 0.5, rtol=1e-6)

    def test_async_writer_flush(self, tmp_path):
        mask = np.ones((8, 8), bool)
        gather = make_pixel_gather(mask, pad_multiple=64)
        out = GeoTIFFOutput(
            ["a"], (0, 1, 0, 0, 0, -1), folder=str(tmp_path),
            async_writes=True,
        )
        for i in range(3):
            out.dump_data(
                datetime.datetime(2020, 1, 1 + i),
                np.full((gather.n_pad, 1), float(i), np.float32),
                None, gather, ["a"],
            )
        out.close()
        assert len(list(tmp_path.glob("*.tif"))) == 3


class TestChunks:
    def test_get_chunks_matches_reference_semantics(self):
        chunks = list(get_chunks(300, 200, (128, 128)))
        # column-major: X outer, Y inner (input_output/utils.py:20-40)
        assert [c.chunk_no for c in chunks] == [1, 2, 3, 4, 5, 6]
        assert chunks[0] == Chunk(0, 0, 128, 128, 1)
        assert chunks[1] == Chunk(0, 128, 128, 72, 2)
        assert chunks[-1] == Chunk(256, 128, 44, 72, 6)

    def test_chunk_mask_and_geotransform(self):
        mask = np.zeros((200, 300), bool)
        mask[130:150, 260:280] = True
        c = list(get_chunks(300, 200, (128, 128)))[-1]
        sub = chunk_mask(mask, c)
        assert sub.shape == (72, 44)
        assert sub.sum() == mask.sum()
        gt = chunk_geotransform((1000.0, 10, 0, 2000.0, 0, -10), c)
        assert gt == (1000.0 + 256 * 10, 10, 0, 2000.0 - 128 * 10, 0, -10)


class TestWindowedRead:
    def _file(self, tmp_path, h=700, w=530, nb=1, tile=256, seed=0):
        rng = np.random.default_rng(seed)
        arr = rng.normal(size=(h, w) if nb == 1 else (h, w, nb))
        arr = arr.astype(np.float32)
        path = str(tmp_path / "win.tif")
        write_geotiff(path, arr, GeoInfo(), tile_size=tile)
        return path, arr

    def test_window_matches_full_read_slice(self, tmp_path):
        from kafka_tpu.io.geotiff import read_geotiff_window
        path, arr = self._file(tmp_path)
        for (r0, c0, nr, nc) in [(0, 0, 700, 530), (100, 200, 50, 60),
                                 (255, 255, 2, 2), (256, 256, 256, 256),
                                 (699, 529, 1, 1), (0, 512, 700, 18)]:
            win, info = read_geotiff_window(path, r0, c0, nr, nc)
            np.testing.assert_array_equal(
                win, arr[r0:r0 + nr, c0:c0 + nc]
            )

    def test_window_past_edge_zero_filled(self, tmp_path):
        from kafka_tpu.io.geotiff import read_geotiff_window
        path, arr = self._file(tmp_path)
        win, _ = read_geotiff_window(path, 690, 520, 20, 20)
        np.testing.assert_array_equal(win[:10, :10], arr[690:, 520:])
        assert (win[10:, :] == 0).all() and (win[:, 10:] == 0).all()

    def test_multiband_window(self, tmp_path):
        from kafka_tpu.io.geotiff import read_geotiff_window
        path, arr = self._file(tmp_path, h=300, w=300, nb=4)
        win, _ = read_geotiff_window(path, 30, 250, 40, 45)
        np.testing.assert_array_equal(win, arr[30:70, 250:295])

    def test_windowed_read_is_partial_io(self, tmp_path):
        """A small window of a big file must not read the whole file."""
        from kafka_tpu.io import geotiff as gt

        path, _ = self._file(tmp_path, h=2048, w=2048)
        total = {"n": 0}
        orig_read = gt._decode_segments

        def counting(segments, info, seg_shape):
            total["n"] += len([s for s in segments if len(s)])
            return orig_read(segments, info, seg_shape)

        gt._decode_segments = counting
        try:
            gt.read_geotiff_window(path, 300, 300, 100, 100)
        finally:
            gt._decode_segments = orig_read
        assert total["n"] == 1  # one 256x256 tile, not all 64


class TestStreamingWriter:
    def test_out_of_order_tiles_and_sparse(self, tmp_path):
        from kafka_tpu.io.geotiff import TiledTiffWriter
        path = str(tmp_path / "s.tif")
        rng = np.random.default_rng(1)
        t_a = rng.normal(size=(256, 256)).astype(np.float32)
        t_b = rng.normal(size=(144, 56)).astype(np.float32)  # edge tile
        with TiledTiffWriter(path, 400, 312, geo=GeoInfo()) as wr:
            wr.write_tile(1, 1, t_b)   # out of order: last tile first
            wr.write_tile(0, 0, t_a)
            # tile (0, 1) and (1, 0) never written -> sparse zeros
        arr, info = read_geotiff(path)
        assert arr.shape == (400, 312)
        np.testing.assert_array_equal(arr[:256, :256], t_a)
        np.testing.assert_array_equal(arr[256:, 256:], t_b)
        assert (arr[:256, 256:] == 0).all()
        assert (arr[256:, :256] == 0).all()

    def test_bigtiff_streaming_roundtrip(self, tmp_path):
        from kafka_tpu.io.geotiff import TiledTiffWriter
        path = str(tmp_path / "big.tif")
        rng = np.random.default_rng(2)
        arr = rng.normal(size=(300, 300)).astype(np.float32)
        with TiledTiffWriter(path, 300, 300, geo=GeoInfo(epsg=32630),
                             bigtiff=True) as wr:
            for y0 in range(0, 300, 256):
                wr.write_rows(y0, arr[y0:y0 + 256])
        back, info = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)
        assert info.geo.epsg == 32630

    def test_unfinished_write_detectable(self, tmp_path):
        from kafka_tpu.io.geotiff import TiledTiffWriter
        path = str(tmp_path / "crash.tif")
        wr = TiledTiffWriter(path, 256, 256)
        wr.write_tile(0, 0, np.ones((256, 256), np.float32))
        # no close(): header still points at IFD offset 0
        with pytest.raises(Exception):
            read_geotiff(path)
        wr.close()
        arr, _ = read_geotiff(path)
        assert (arr == 1).all()

    def test_strip_negative_window(self, tmp_path):
        """Windows starting left/above the raster must zero-fill, not wrap
        via Python negative indexing (strip layout)."""
        import struct
        import zlib as _z
        from kafka_tpu.io.geotiff import read_geotiff_window
        # hand-build a tiny single-strip uncompressed TIFF (strips are a
        # read-only layout here; the writer emits tiles)
        h = w = 8
        arr = np.arange(h * w, dtype=np.uint8).reshape(h, w)
        data = arr.tobytes()
        entries = [
            (256, 3, [w]), (257, 3, [h]), (258, 3, [8]), (259, 3, [1]),
            (262, 3, [1]), (273, 4, [8 + 2 + 12 * 9 + 4]), (277, 3, [1]),
            (278, 3, [h]), (279, 4, [len(data)]),
        ]
        buf = struct.pack("<2sHI", b"II", 42, 8)
        buf += struct.pack("<H", len(entries))
        for tag, typ, vals in entries:
            fmt = {3: "H", 4: "I"}[typ]
            raw = struct.pack("<" + fmt * len(vals), *vals)
            buf += struct.pack("<HHI", tag, typ, len(vals))
            buf += raw.ljust(4, b"\x00")
        buf += struct.pack("<I", 0) + data
        path = str(tmp_path / "strip.tif")
        with open(path, "wb") as f:
            f.write(buf)
        win, _ = read_geotiff_window(path, -2, -3, 6, 6)
        assert (win[:2, :] == 0).all() and (win[:, :3] == 0).all()
        np.testing.assert_array_equal(win[2:, 3:], arr[:4, :3])


class TestFloatPredictor:
    """TIFF predictor 3 (floating-point differencing, libtiff fpDiff/fpAcc
    layout) — lossless, and both faster and smaller than raw-byte DEFLATE
    for float rasters."""

    def test_roundtrip_single_band(self, tmp_path):
        rng = np.random.default_rng(7)
        # smooth field + noise, like real analysis outputs
        yy, xx = np.mgrid[:300, :280]
        arr = (np.sin(yy / 40.0) * np.cos(xx / 30.0) +
               rng.normal(0, 0.01, (300, 280))).astype(np.float32)
        p = str(tmp_path / "fp.tif")
        write_geotiff(p, arr, GeoInfo(epsg=32630), predictor=3)
        back, info = read_geotiff(p)
        assert info.predictor == 3
        np.testing.assert_array_equal(np.asarray(back), arr)

    def test_roundtrip_multiband_and_special_values(self, tmp_path):
        arr = np.zeros((64, 64, 3), np.float32)
        arr[..., 0] = np.nan
        arr[..., 1] = np.inf
        arr[10:20, 10:20, 2] = -1e-38  # subnormal-ish
        p = str(tmp_path / "fp3.tif")
        write_geotiff(p, arr, GeoInfo(), predictor=3)
        back, _ = read_geotiff(p)
        np.testing.assert_array_equal(
            np.asarray(back).view(np.uint32), arr.view(np.uint32)
        )

    def test_windowed_read_with_predictor3(self, tmp_path):
        rng = np.random.default_rng(8)
        arr = rng.normal(size=(600, 520)).astype(np.float32)
        p = str(tmp_path / "fpw.tif")
        write_geotiff(p, arr, GeoInfo(), predictor=3)
        from kafka_tpu.io.geotiff import read_geotiff_window
        win, _ = read_geotiff_window(p, 100, 250, 80, 90)
        np.testing.assert_array_equal(win, arr[100:180, 250:340])

    def test_predictor3_rejects_non_float32(self, tmp_path):
        with pytest.raises(ValueError):
            write_geotiff(
                str(tmp_path / "x.tif"),
                np.zeros((8, 8), np.uint16), GeoInfo(), predictor=3,
            )

    def test_compresses_better_than_raw(self, tmp_path):
        yy, xx = np.mgrid[:512, :512]
        rng = np.random.default_rng(9)
        arr = (0.3 + 0.1 * np.sin(yy / 25.0) +
               rng.normal(0, 0.005, (512, 512))).astype(np.float32)
        p1 = str(tmp_path / "p1.tif")
        p3 = str(tmp_path / "p3.tif")
        write_geotiff(p1, arr, GeoInfo(), predictor=1)
        write_geotiff(p3, arr, GeoInfo(), predictor=3)
        assert os.path.getsize(p3) < os.path.getsize(p1)


class TestNativeFp3Codec:
    """The fused C++ predictor-3 chain must be bit-exact against the
    numpy reference path, through both the raw segment API and the real
    file read/write API."""

    def test_segment_parity_and_roundtrip(self):
        from kafka_tpu.io import native_codec
        from kafka_tpu.io.geotiff import (
            _fp_predict_decode, _fp_predict_encode,
        )

        if native_codec.encode_fp3_many(
            np.zeros((1, 4, 4, 1), np.float32)
        ) is None:
            pytest.skip("native fp3 codec unavailable")
        rng = np.random.default_rng(3)
        tiles = rng.normal(size=(5, 32, 48, 2)).astype(np.float32)
        segs = native_codec.encode_fp3_many(tiles, level=6)
        import zlib

        for i in range(len(tiles)):
            assert zlib.decompress(segs[i]) == _fp_predict_encode(
                tiles[i]
            )
        dec = native_codec.decode_fp3_many(segs, 32, 48, 2,
                                           compressed=True)
        np.testing.assert_array_equal(dec, tiles)
        # empty segment -> zero tile (sparse-file contract)
        dec2 = native_codec.decode_fp3_many([b"", segs[0]], 32, 48, 2,
                                            compressed=True)
        assert (dec2[0] == 0).all()
        np.testing.assert_array_equal(dec2[1], tiles[0])

    def test_file_roundtrip_native_equals_fallback(self, tmp_path,
                                                   monkeypatch):
        from kafka_tpu.io import native_codec
        from kafka_tpu.io.geotiff import read_geotiff, write_geotiff

        rng = np.random.default_rng(4)
        arr = rng.normal(size=(300, 200)).astype(np.float32)
        write_geotiff(str(tmp_path / "native.tif"), arr,
                      predictor=3, level=1)
        # Force the pure-python path for both encode and decode.
        monkeypatch.setattr(native_codec, "_native", False)
        write_geotiff(str(tmp_path / "python.tif"), arr,
                      predictor=3, level=1)
        a_py, _ = read_geotiff(str(tmp_path / "native.tif"))
        b_py, _ = read_geotiff(str(tmp_path / "python.tif"))
        monkeypatch.undo()
        a_nat, _ = read_geotiff(str(tmp_path / "native.tif"))
        b_nat, _ = read_geotiff(str(tmp_path / "python.tif"))
        for got in (a_py, b_py, a_nat, b_nat):
            np.testing.assert_array_equal(got, arr)


class TestLZW:
    """TIFF LZW (GDAL's default creation option): writer compatibility
    mode, the Python reference decoder, and the ~60x native batch
    decoder must all agree bit for bit."""

    def _cases(self):
        rng = np.random.default_rng(12)
        return [
            b"",
            b"A",
            b"ABABABABABAB" * 50,                       # KwKwK-heavy
            bytes(rng.integers(0, 8, 5000, dtype=np.uint8)),
            # incompressible: exercises width growth 9->12 + CLEAR resets
            bytes(rng.integers(0, 256, 20000, dtype=np.uint8)),
            (b"TOBEORNOTTOBEORTOBEORNOT" * 300),
        ]

    def test_encoder_decoder_roundtrip(self):
        from kafka_tpu.io.geotiff import _lzw_decode, lzw_encode

        for i, raw in enumerate(self._cases()):
            assert _lzw_decode(lzw_encode(raw)) == raw, i

    def test_native_matches_python_decoder(self):
        from kafka_tpu.io import native_codec
        from kafka_tpu.io.geotiff import lzw_encode

        encs = [lzw_encode(raw) for raw in self._cases()]
        expected = max(len(r) for r in self._cases())
        got = native_codec.lzw_inflate_many(encs, expected)
        if got is None:
            pytest.skip("native LZW unavailable")
        assert got == self._cases()

    @pytest.mark.parametrize("dtype,predictor", [
        (np.float32, 1), (np.uint16, 2), (np.float32, 3),
    ])
    def test_lzw_file_roundtrip(self, tmp_path, dtype, predictor):
        from kafka_tpu.io.geotiff import read_info

        if np.issubdtype(dtype, np.floating):
            arr = RNG.normal(size=(70, 90)).astype(dtype)
        else:
            arr = RNG.integers(0, 900, size=(70, 90)).astype(dtype)
        path = str(tmp_path / "lzw.tif")
        write_geotiff(path, arr, compress="lzw", predictor=predictor,
                      tile_size=64)
        info = read_info(path)
        assert info.compression == 5
        back, _ = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)

    def test_lzw_file_python_fallback(self, tmp_path, monkeypatch):
        from kafka_tpu.io import native_codec

        arr = RNG.normal(size=(40, 40)).astype(np.float32)
        path = str(tmp_path / "lzw_fb.tif")
        write_geotiff(path, arr, compress="lzw")
        monkeypatch.setattr(native_codec, "_native", False)
        back, _ = read_geotiff(path)
        np.testing.assert_array_equal(back, arr)

    def test_width_boundary_sweep(self):
        """Round-trip incompressible streams whose lengths sweep across
        every decoder width boundary (511/1023/2047): the final-code
        width bump (libtiff LZWPostEncode) must keep the EOI readable —
        the round-3 review caught exactly this class failing."""
        from kafka_tpu.io import native_codec
        from kafka_tpu.io.geotiff import _lzw_decode, lzw_encode

        rng = np.random.default_rng(99)
        spans = list(range(240, 275)) + list(range(750, 790)) + \
            list(range(1770, 1810, 2))
        raws, encs = [], []
        for n in spans:
            raw = bytes(rng.integers(0, 256, n, dtype=np.uint8))
            enc = lzw_encode(raw)
            assert _lzw_decode(enc) == raw, f"python decoder at n={n}"
            raws.append(raw)
            encs.append(enc)
        got = native_codec.lzw_inflate_many(encs, max(spans))
        if got is not None:
            assert got == raws

    def test_native_encoder_streams_match_python(self):
        """rk_lzw_deflate_batch must emit bit-identical streams to the
        Python lzw_encode (same width-switch/clear/EOI policy)."""
        from kafka_tpu.io import native_codec
        from kafka_tpu.io.geotiff import lzw_encode

        raws = self._cases()
        got = native_codec.lzw_deflate_many(raws)
        if got is None:
            pytest.skip("native LZW encoder unavailable")
        assert got == [lzw_encode(r) for r in raws]
