"""Temporal fusion: K consecutive windows as one lax.scan program must be
numerically indistinguishable from the host-driven per-window loop."""

import datetime

import numpy as np
import pytest

from kafka_tpu.core.propagators import (
    propagate_information_filter,
    tip_prior,
)
from kafka_tpu.engine import KalmanFilter
from kafka_tpu.engine.priors import FixedGaussianPrior, TIP_PARAMETER_LIST
from kafka_tpu.obsops.twostream import TwoStreamOperator
from kafka_tpu.testing import MemoryOutput, SyntheticObservations

RNG = np.random.default_rng(7)


def day(i):
    return datetime.datetime(2018, 5, 1) + datetime.timedelta(days=i)


def pivot_mask(ny=14, nx=18, r=6):
    yy, xx = np.mgrid[:ny, :nx]
    return (yy - ny // 2) ** 2 + (xx - nx // 2) ** 2 < r * r


def tip_truth(mask, seed=3):
    # Seeded per call: both runs of a parity pair must see THE SAME truth.
    rng = np.random.default_rng(seed)
    base = np.asarray(tip_prior().mean)
    truth = np.broadcast_to(base, mask.shape + (7,)).copy()
    truth[..., 6] = np.clip(
        0.45 + 0.1 * rng.standard_normal(mask.shape), 0.1, 0.9
    ).astype(np.float32)
    return truth.astype(np.float32)


def run_pipeline(scan_window, n_days=9, grid_step=1, checkpointer=None,
                 state_propagation=propagate_information_filter,
                 prior=None, mask=None, checkpoint_every_n=1,
                 solver_options=None):
    mask = pivot_mask() if mask is None else mask
    op = TwoStreamOperator()
    truth = tip_truth(mask)
    obs = SyntheticObservations(
        dates=[day(i) for i in range(1, n_days)],
        operator=op,
        truth_fn=lambda date: truth,
        sigma=0.03,
        mask_prob=0.1,
    )
    out = MemoryOutput()
    # Damped Gauss-Newton so every window CONVERGES: a solve that bails at
    # the 26-iteration cap returns an oscillating iterate, where the tiny
    # float-reassociation differences between the fused (one program) and
    # host-driven paths amplify chaotically — parity is only meaningful on
    # converged solves.
    kf = KalmanFilter(
        obs, out, mask, TIP_PARAMETER_LIST,
        state_propagation=state_propagation,
        prior=prior,
        pad_multiple=128,
        scan_window=scan_window,
        solver_options={"relaxation": 0.7, **(solver_options or {})},
        checkpoint_every_n=checkpoint_every_n,
    )
    kf.set_trajectory_model()
    kf.set_trajectory_uncertainty(np.full(7, 1e-3, np.float32))
    p0 = FixedGaussianPrior(tip_prior(), TIP_PARAMETER_LIST)
    x0, p_inv0 = p0.process_prior(None, kf.gather)
    grid = [day(i) for i in range(0, n_days + 1, grid_step)]
    x_a, _, p_inv_a = kf.run(grid, x0, None, p_inv0,
                             checkpointer=checkpointer)
    return kf, out, np.asarray(x_a), np.asarray(p_inv_a), mask


class TestFusedParity:
    def test_fused_matches_unfused(self):
        kf1, out1, x1, pi1, mask = run_pipeline(scan_window=1)
        kf4, out4, x4, pi4, _ = run_pipeline(scan_window=4)

        # fusion actually engaged (and only in the fused run)
        assert any("fused" in r for r in kf4.diagnostics_log)
        assert not any("fused" in r for r in kf1.diagnostics_log)

        # Parity is bounded by the Gauss-Newton convergence tolerance
        # (1e-3 on the normalised step): the fused program's float
        # reassociation can change WHERE inside the tolerance ball each
        # window converges, and those differences chain.  Anything beyond
        # ~tol would be a real semantic bug (wrong window pairing, wrong
        # advance...), which is what this guards.
        np.testing.assert_allclose(x4, x1, atol=2e-3)
        # A = J^T R^-1 J is quadratically sensitive to the linearisation
        # point, so individual entries can move a few % within the state
        # tolerance ball; the user-facing sigma rasters below stay tight.
        np.testing.assert_allclose(pi4, pi1, rtol=1e-1, atol=1e-1)
        assert sorted(out1.output) == sorted(out4.output)
        for ts in out1.output:
            for key, raster in out1.output[ts].items():
                np.testing.assert_allclose(
                    out4.output[ts][key], raster, rtol=1e-2, atol=2e-3,
                    err_msg=f"{ts} {key}",
                )

    def test_fused_with_date_invariant_prior(self):
        prior = FixedGaussianPrior(tip_prior(), TIP_PARAMETER_LIST)
        kf1, out1, x1, _, _ = run_pipeline(
            scan_window=1, state_propagation=None, prior=prior
        )
        kf4, out4, x4, _, _ = run_pipeline(
            scan_window=4, state_propagation=None, prior=prior
        )
        assert any("fused" in r for r in kf4.diagnostics_log)
        np.testing.assert_allclose(x4, x1, atol=2e-5)
        for ts in out1.output:
            np.testing.assert_allclose(
                out4.output[ts]["TeLAI"], out1.output[ts]["TeLAI"],
                atol=2e-4,
            )

    def test_fused_scan_composes_with_pallas(self):
        """``use_pallas`` threads through the scan as a static argument:
        the fused-kernel + fused-scan run must engage temporal fusion AND
        match both the unfused pallas run and the fused XLA run."""
        opts = {"use_pallas": True}
        kf_p, out_p, x_p, _, mask = run_pipeline(
            scan_window=4, solver_options=opts
        )
        assert any("fused" in r for r in kf_p.diagnostics_log), \
            "use_pallas must no longer veto temporal fusion"
        kf_u, out_u, x_u, _, _ = run_pipeline(
            scan_window=1, solver_options=opts, mask=mask
        )
        kf_x, out_x, x_x, _, _ = run_pipeline(
            scan_window=4, mask=mask
        )
        # Same tolerance reasoning as test_fused_matches_unfused: parity
        # is bounded by the GN tolerance ball, everything beyond ~tol is
        # a real semantic bug (dropped flag, wrong window pairing...).
        np.testing.assert_allclose(x_p, x_u, atol=2e-3)
        np.testing.assert_allclose(x_p, x_x, atol=2e-3)

    @pytest.mark.slow
    def test_fused_scan_inkernel_linearize_end_to_end(self):
        """The in-kernel Gauss-Newton path (operator-advertised analytic
        linearisation, whole GN loop inside the Pallas kernel) through
        the FULL production pipeline with temporal fusion: the
        ``assimilate_windows_scan`` program with ``inkernel_linearize``
        (the default for capable operators) must engage fusion and match
        both the out-of-kernel Pallas run and the XLA run."""
        kf_ik, out_ik, x_ik, _, mask = run_pipeline(
            scan_window=4, solver_options={"use_pallas": True}
        )
        assert any("fused" in r for r in kf_ik.diagnostics_log), \
            "in-kernel linearise must not veto temporal fusion"
        kf_pl, out_pl, x_pl, _, _ = run_pipeline(
            scan_window=4, mask=mask,
            solver_options={"use_pallas": True,
                            "inkernel_linearize": False},
        )
        kf_x, out_x, x_x, _, _ = run_pipeline(scan_window=4, mask=mask)
        # GN tolerance-ball reasoning as above: anything beyond ~tol is
        # a real semantic bug (dropped capability, wrong carry...).
        np.testing.assert_allclose(x_ik, x_pl, atol=2e-3)
        np.testing.assert_allclose(x_ik, x_x, atol=2e-3)
        # User-facing rasters agree window by window.
        for ts in out_x.output:
            np.testing.assert_allclose(
                out_ik.output[ts]["TeLAI"], out_x.output[ts]["TeLAI"],
                atol=2e-3, err_msg=str(ts),
            )

    def test_multidate_window_breaks_block_not_correctness(self):
        # grid_step=3 puts 3 acquisitions in each window -> no fusion
        # (len(locate_times) != 1), result identical to the unfused run.
        kf1, out1, x1, _, _ = run_pipeline(scan_window=1, grid_step=3)
        kf4, out4, x4, _, _ = run_pipeline(scan_window=4, grid_step=3)
        assert not any("fused" in r for r in kf4.diagnostics_log)
        np.testing.assert_allclose(x4, x1, atol=1e-6)

    def test_diagnostics_per_fused_window(self):
        kf4, _, _, _, _ = run_pipeline(scan_window=4)
        fused = [r for r in kf4.diagnostics_log if "fused" in r]
        assert fused and all(r["n_iterations"] >= 2 for r in fused)
        assert all(np.isfinite(r["convergence_norm"]) for r in fused)


class TestFusedCheckpoint:
    def test_checkpoint_saved_at_block_end_resumes(self, tmp_path):
        from kafka_tpu.engine.checkpoint import Checkpointer

        ck = Checkpointer(str(tmp_path))
        kf, out, x_fin, pi_fin, mask = run_pipeline(
            scan_window=4, checkpointer=ck
        )
        # a checkpoint exists for the final fused-block end
        resume = Checkpointer(str(tmp_path))
        ts, x_ck, p_inv_ck = resume.load_latest()
        assert ts == max(out.output)
        np.testing.assert_allclose(np.asarray(x_ck), x_fin, atol=1e-6)


class TestGeoTIFFBlockDump:
    def test_dump_block_files_match_per_date(self, tmp_path):
        import jax.numpy as jnp

        from kafka_tpu.engine.state import make_pixel_gather
        from kafka_tpu.io import GeoTIFFOutput, read_geotiff

        mask = np.ones((6, 9), bool)
        g = make_pixel_gather(mask, pad_multiple=64)
        k = 3
        xs = RNG.uniform(0.1, 1.0, (k, g.n_pad, 2)).astype(np.float32)
        diags = RNG.uniform(1.0, 30.0, (k, g.n_pad, 2)).astype(np.float32)
        ts = [day(i) for i in range(k)]

        blk = GeoTIFFOutput(["a", "b"], (0, 1, 0, 0, 0, -1),
                            folder=str(tmp_path / "blk"))
        blk.dump_block(ts, jnp.asarray(xs), jnp.asarray(diags), g,
                       ["a", "b"])
        one = GeoTIFFOutput(["a", "b"], (0, 1, 0, 0, 0, -1),
                            folder=str(tmp_path / "one"))
        for i, t in enumerate(ts):
            one.dump_data(t, jnp.asarray(xs[i]), jnp.asarray(diags[i]),
                          g, ["a", "b"])
        for f in sorted((tmp_path / "one").glob("*.tif")):
            a, _ = read_geotiff(str(f))
            b, _ = read_geotiff(str(tmp_path / "blk" / f.name))
            np.testing.assert_array_equal(a, b, err_msg=f.name)


class TestCheckpointCadence:
    def test_every_n_reduces_saves_and_last_always_saved(self, tmp_path):
        from kafka_tpu.engine import Checkpointer

        ck1 = Checkpointer(str(tmp_path / "every1"))
        kf1, *_ = run_pipeline(scan_window=1, checkpointer=ck1)
        saved1 = [ts for ts, _ in ck1.list_checkpoints()]

        ck3 = Checkpointer(str(tmp_path / "every3"))
        kf3, *_ = run_pipeline(
            scan_window=1, checkpointer=ck3, checkpoint_every_n=3
        )
        saved3 = [ts for ts, _ in ck3.list_checkpoints()]

        assert len(saved1) > len(saved3) >= 1
        # The run's final window must always checkpoint, whatever the
        # cadence, or resume could never complete a finished chunk.
        assert max(saved3) == max(saved1)
        # Cadence-3 saves every third processed window (plus the last).
        assert len(saved3) == -(-len(saved1) // 3) or \
            len(saved3) == len(saved1) // 3 + 1

    def test_cadenced_resume_matches_full_run(self, tmp_path):
        """Killing a cadenced run and resuming from its last checkpoint
        must reproduce the uninterrupted run's final state."""
        from kafka_tpu.engine import Checkpointer

        ck = Checkpointer(str(tmp_path / "ck"))
        kf_full, out_full, x_full, _, mask = run_pipeline(
            scan_window=1, checkpointer=ck, checkpoint_every_n=4
        )
        # Fresh pipeline resuming from the saved state over the SAME grid.
        ck2 = Checkpointer(str(tmp_path / "ck"))
        grid = [day(i) for i in range(0, 10)]
        rest, seed = ck2.resume_time_grid(grid)
        assert seed is not None
        # The last checkpoint was the final window -> nothing left to do.
        assert len(rest) == 1
        np.testing.assert_allclose(
            np.asarray(seed[0]), x_full, atol=1e-6
        )


class TestFusedConvergedMask:
    def test_converged_frac_reported_on_both_paths(self):
        opts = {"per_pixel_convergence": True}
        kf_f, out_f, x_f, _, mask = run_pipeline(
            scan_window=4, solver_options=opts
        )
        kf_u, out_u, x_u, _, _ = run_pipeline(
            scan_window=1, solver_options=opts, mask=mask
        )
        fused_recs = [r for r in kf_f.diagnostics_log if r.get("fused")]
        assert fused_recs, "expected fused windows"
        for rec in fused_recs:
            assert 0.0 <= rec["converged_frac"] <= 1.0
        # The damped TIP problem converges essentially everywhere.
        assert fused_recs[-1]["converged_frac"] > 0.95
        unfused_recs = [
            r for r in kf_u.diagnostics_log if not r.get("fused")
        ]
        assert unfused_recs and all(
            "converged_frac" in r for r in unfused_recs
        )
        # Slightly wider than the global-norm parity (2e-3): per-pixel
        # mode freezes each pixel at its first converged iterate, and the
        # fused program's float reassociation can freeze a borderline
        # pixel one iteration earlier/later — up to ~2 tolerance balls
        # apart (observed max |dx| = 2.7e-3), still far below anything a
        # semantic bug would produce.
        np.testing.assert_allclose(x_f, x_u, atol=5e-3)
