"""Coalesced serving (ISSUE 20): the admission micro-window, batched
device launches, and AOT-compiled shape buckets.

Acceptance pins:

- partition invariance: ANY partition of K compatible requests into
  admission groups yields byte-identical payloads (x_sha256,
  solver_health, quality) to serving them one at a time — including
  mixed cache-hit/miss groups and a mid-batch poison member erroring
  ALONE while its peers' answers stay bit-identical;
- every served_from path a coalesced member can take (cold, warm,
  warm_noop, cache) is bit-identical to the solo path;
- a partially-filled micro-window flushes IMMEDIATELY on drain — a
  SIGTERM never waits out the window (drain-latency regression);
- AOT restart contract: a second daemon start over a warm
  --compile-cache-dir serves its first request with zero
  kafka_compile_cache_misses_total for the declared buckets;
- the loadgen rows: under concurrent compatible load the mean admission
  group size exceeds 1 and the queue_wait p99 drops vs the same load
  with the window off.

All tier-1 / CPU.
"""

import datetime
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from kafka_tpu import telemetry
from kafka_tpu.resilience import POISON, RetryPolicy, faults
from kafka_tpu.serve import (
    AdmissionPolicy,
    AssimilationService,
    TileSession,
    make_synthetic_tile,
    read_response,
    submit_request,
    synthetic_dates,
)
from kafka_tpu.serve import batch as batching
from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
from kafka_tpu.telemetry import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the default synthetic tile's observation calendar (see test_serve).
DATES = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)

#: cold / warm_noop / warm ladder: D1 assimilates its whole grid window,
#: so D2 (same window, different calendar date — a DISTINCT result-cache
#: key) is a warm_noop, and D3 (next window) is a warm incremental.
D1, D2, D3 = DATES[0], DATES[1], DATES[2]

FAST2 = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _subprocess_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop(faults.ENV_VAR, None)
    return env


def _sig(body):
    """The payload identity the partition property quantifies over."""
    return (
        body.get("x_sha256"),
        body.get("solver_health"),
        body.get("quality"),
    )


def _batch_stamp(body):
    trace = body.get("trace") or {}
    return trace.get("batch_id"), trace.get("batch_size")


class _Bucket:
    def __init__(self, key):
        self.key = key


class BucketStubSession:
    """Duck-typed session WITH a shape bucket: exercises the admission
    micro-window deterministically.  No JAX — a member that never
    dispatches simply leaves the rendezvous, so the stub's sleep models
    the per-tile solve the window lets run concurrently."""

    def __init__(self, name, key="bucket0", sleep_s=0.0):
        self.name = name
        self._key = key
        self.sleep_s = sleep_s
        self.serves = 0

    def serve_bucket(self):
        return None if self._key is None else _Bucket((self._key,))

    def serve(self, date, smoothed=False, dispatcher=None):
        self.serves += 1
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return {
            "status": "ok", "x_sha256": f"stub-{self.name}",
            "date": date.isoformat(), "served_from": "cold",
        }


def stub_batch_service(tmp_path, names, window_ms=250.0, max_batch=8,
                       key="bucket0", sleep_s=0.0, keys=None):
    sessions = {
        n: BucketStubSession(
            n, key=(keys[i] if keys is not None else key),
            sleep_s=sleep_s,
        )
        for i, n in enumerate(names)
    }
    svc = AssimilationService(
        sessions, str(tmp_path),
        policy=AdmissionPolicy(max_queue_depth=64),
        retry_policy=FAST2,
        batch_window_ms=window_ms, max_batch=max_batch,
    )
    return svc, sessions


def _submit_group(svc, reqs):
    for tile, date, rid in reqs:
        svc.submit({
            "tile": tile, "date": date.isoformat(), "request_id": rid,
        })
    return {
        rid: svc.result(rid, timeout_s=120) for _, _, rid in reqs
    }


# ---------------------------------------------------------------------------
# micro-window mechanics (stub sessions: deterministic, no JAX)
# ---------------------------------------------------------------------------

class TestMicroWindow:
    def test_window_coalesces_compatible_tiles(self, tmp_path):
        """Four compatible single-date requests land in ONE admission
        group: shared batch_id, batch_size 4 on every response trace,
        and the group counters move once."""
        with telemetry.use(MetricsRegistry()) as reg:
            names = [f"t{i}" for i in range(4)]
            svc, sessions = stub_batch_service(
                tmp_path, names, window_ms=2000.0, max_batch=4,
            )
            svc.start()
            try:
                got = _submit_group(svc, [
                    (n, D1, f"r-{n}") for n in names
                ])
                stamps = {r: _batch_stamp(b) for r, b in got.items()}
                assert all(b["status"] == "ok" for b in got.values())
                ids = {s[0] for s in stamps.values()}
                assert len(ids) == 1 and None not in ids
                assert all(s[1] == 4 for s in stamps.values())
                assert reg.value("kafka_serve_batches_total") == 1
                assert reg.value(
                    "kafka_serve_batch_requests_total") == 4
                assert all(s.serves == 1 for s in sessions.values())
            finally:
                svc.close()

    def test_same_tile_and_smoothed_never_mix(self, tmp_path):
        """A same-tile peer and a smoothed request are never coalesced:
        tile sessions are single-threaded, and reanalysis is a different
        product (different launch structure) from the forward serve."""
        with telemetry.use(MetricsRegistry()) as reg:
            svc, sessions = stub_batch_service(
                tmp_path, ["t0", "t1"], window_ms=150.0, max_batch=8,
            )
            svc.start()
            try:
                got = _submit_group(svc, [
                    ("t0", D1, "a"), ("t0", D2, "a2"), ("t1", D1, "b"),
                ])
                # t0+t1 coalesce; the second t0 request serves alone.
                assert _batch_stamp(got["a"])[1] == 2
                assert _batch_stamp(got["b"])[1] == 2
                assert _batch_stamp(got["a2"]) == (None, None)
                # A smoothed head flushes immediately, never batched.
                svc.submit({"tile": "t0", "date": D3.isoformat(),
                            "request_id": "sm", "smoothed": True})
                sm = svc.result("sm", timeout_s=30)
                assert sm["status"] == "ok"
                assert _batch_stamp(sm) == (None, None)
                assert reg.value("kafka_serve_batches_total") == 1
            finally:
                svc.close()

    def test_incompatible_buckets_do_not_mix(self, tmp_path):
        """Different shape-bucket keys (and bucketless duck-typed
        sessions) never share an admission group."""
        with telemetry.use(MetricsRegistry()) as reg:
            svc, _ = stub_batch_service(
                tmp_path, ["t0", "t1", "t2"], window_ms=100.0,
                keys=["ka", "kb", None],
            )
            svc.start()
            try:
                got = _submit_group(svc, [
                    ("t0", D1, "a"), ("t1", D1, "b"), ("t2", D1, "c"),
                ])
                assert all(b["status"] == "ok" for b in got.values())
                assert all(
                    _batch_stamp(b) == (None, None)
                    for b in got.values()
                )
                assert reg.value("kafka_serve_batches_total") is None
            finally:
                svc.close()

    def test_drain_flushes_partial_window_immediately(self, tmp_path):
        """The drain-latency regression (satellite): a SIGTERM drain
        must not wait out a partially-filled 10 s window — the open
        window flushes the moment draining starts."""
        with telemetry.use(MetricsRegistry()):
            svc, _ = stub_batch_service(
                tmp_path, ["t0", "t1"], window_ms=10_000.0,
            )
            svc.start()
            try:
                svc.submit({"tile": "t0", "date": D1.isoformat(),
                            "request_id": "r1"})
                # Let the worker dequeue r1 and open the window.
                deadline = time.monotonic() + 5
                while svc.pending() and time.monotonic() < deadline:
                    time.sleep(0.005)
                time.sleep(0.05)
                t0 = time.monotonic()
                svc.stop_admitting()
                assert svc.drain(timeout_s=30)
                got = svc.result("r1", timeout_s=1)
                waited = time.monotonic() - t0
                assert got is not None and got["status"] == "ok"
                assert waited < 2.0, (
                    f"drain waited {waited:.1f}s on an open 10s window"
                )
            finally:
                svc.close()

    def test_replayed_requests_flush_immediately(self, tmp_path):
        """Journal replay is recovery, not interactive traffic: a
        replayed request never waits out the window (nor batches)."""
        with telemetry.use(MetricsRegistry()) as reg:
            svc, _ = stub_batch_service(tmp_path, ["t0", "t1"])
            svc.start()
            try:
                faults.script("serve.respond", "1", POISON)
                svc.submit({"tile": "t0", "date": D1.isoformat(),
                            "request_id": "r1"})
                deadline = time.monotonic() + 30
                while reg.value("kafka_serve_respond_errors_total") \
                        is None and time.monotonic() < deadline:
                    time.sleep(0.01)
            finally:
                svc.close()
            faults.reset()
            # "Restart" with a LONG window: replay answers fast anyway.
            svc2, _ = stub_batch_service(
                tmp_path, ["t0", "t1"], window_ms=10_000.0,
            )
            t0 = time.monotonic()
            svc2.start()
            try:
                r1 = svc2.result("r1", timeout_s=5)
                waited = time.monotonic() - t0
                assert r1 is not None and r1["status"] == "ok"
                assert _batch_stamp(r1) == (None, None)
                assert waited < 2.0
                assert reg.value("kafka_serve_replayed_total") == 1
            finally:
                svc2.close()


# ---------------------------------------------------------------------------
# the loadgen rows: coalescing shrinks queue_wait under compatible load
# ---------------------------------------------------------------------------

class TestLoadgenBatchRows:
    def test_batched_load_shrinks_queue_wait(self, tmp_path):
        """Eight concurrent compatible requests against sleeping stub
        tiles: with the window on, the group serves concurrently (mean
        batch size 8, queue_wait collapses); with the window off (the
        runtime toggle), the same load serializes and the queue_wait
        p99 balloons — the row pair the sweep bench gates on."""
        from tools.loadgen import _Target, run_load

        with telemetry.use(MetricsRegistry()):
            names = [f"t{i}" for i in range(8)]
            svc, _ = stub_batch_service(
                tmp_path, names, window_ms=500.0, max_batch=8,
                sleep_s=0.08,
            )
            svc.start()
            try:
                plan = [
                    {"tile": n, "date": D1.isoformat(),
                     "request_id": f"bat-{n}"}
                    for n in names
                ]
                batched = run_load(_Target(service=svc), plan,
                                   concurrency=8, timeout_s=60)
                svc.set_batch_window(0.0)
                plan = [
                    {"tile": n, "date": D2.isoformat(),
                     "request_id": f"unb-{n}"}
                    for n in names
                ]
                unbatched = run_load(_Target(service=svc), plan,
                                     concurrency=8, timeout_s=60)
            finally:
                svc.close()
        assert batched["serve_ok_total"] == 8
        assert unbatched["serve_ok_total"] == 8
        assert batched["serve_batch_mean_size"] == 8.0
        assert batched["serve_batch_coalesced_total"] == 8
        assert batched["serve_solved_total"] == 8
        assert unbatched["serve_batch_mean_size"] == 1.0
        assert unbatched["serve_batch_coalesced_total"] == 0
        # 8 x 80 ms serialized vs one concurrent group: the window is
        # what keeps the queue from stacking.
        assert batched["serve_queue_wait_p99_ms"] < \
            unbatched["serve_queue_wait_p99_ms"]


# ---------------------------------------------------------------------------
# partition invariance + served_from-path parity (real tiles, real solves)
# ---------------------------------------------------------------------------

def _tile(tmp_path, name, seed):
    return TileSession(make_synthetic_tile(
        name, str(tmp_path / f"ck_{name}_{seed}"), seed=seed,
    ))


def _real_service(tmp_path, tag, seeds, window_ms=1500.0, max_batch=2):
    sessions = {
        f"t{k}": _tile(tmp_path, f"{tag}t{k}", seed)
        for k, seed in enumerate(seeds)
    }
    svc = AssimilationService(
        sessions, str(tmp_path / f"root_{tag}"),
        policy=AdmissionPolicy(max_queue_depth=64),
        batch_window_ms=window_ms, max_batch=max_batch,
    )
    return svc, sessions


class TestPartitionBitIdentity:
    """The satellite property: partitions of compatible requests into
    admission groups are payload-invariant, across every served_from
    path a member can take."""

    SEEDS = {"t0": 1, "t1": 2, "t2": 3}

    def test_partitions_and_served_from_paths(self, tmp_path):
        """One service, one ladder: {t0,t1} batched + {t2} solo at D1
        (cold), {t0,t1} batched at D2 (warm_noop) and D3 (warm), then a
        mixed cache-hit/miss group — every payload byte-identical to
        the one-at-a-time baselines."""
        base = {}
        for t in ("t0", "t1", "t2"):
            sess = _tile(tmp_path, f"solo{t}", self.SEEDS[t])
            for d in (D1, D2, D3):
                r = sess.serve(d)
                base[(t, d)] = (_sig(r), r["served_from"])
        assert base[("t0", D1)][1] == "cold"
        assert base[("t0", D2)][1] == "warm_noop"
        assert base[("t0", D3)][1] == "warm"

        with telemetry.use(MetricsRegistry()):
            svc, _ = _real_service(
                tmp_path, "p1", [self.SEEDS[t] for t in
                                 ("t0", "t1", "t2")],
            )
            svc.start()
            try:
                # cold, batched {t0,t1} + solo {t2}.
                got = _submit_group(svc, [
                    ("t0", D1, "c0"), ("t1", D1, "c1"),
                ])
                got.update(_submit_group(svc, [("t2", D1, "c2")]))
                assert _batch_stamp(got["c0"])[1] == 2
                assert _batch_stamp(got["c0"])[0] == \
                    _batch_stamp(got["c1"])[0]
                assert _batch_stamp(got["c2"]) == (None, None)
                for rid, tile in (("c0", "t0"), ("c1", "t1"),
                                  ("c2", "t2")):
                    assert got[rid]["served_from"] == "cold"
                    assert _sig(got[rid]) == base[(tile, D1)][0], rid
                # warm_noop, batched: same grid window, new date.
                got = _submit_group(svc, [
                    ("t0", D2, "n0"), ("t1", D2, "n1"),
                ])
                for rid, tile in (("n0", "t0"), ("n1", "t1")):
                    assert got[rid]["served_from"] == "warm_noop"
                    assert _batch_stamp(got[rid])[1] == 2
                    assert _sig(got[rid]) == base[(tile, D2)][0], rid
                # warm incremental, batched: the next grid window.
                got = _submit_group(svc, [
                    ("t0", D3, "w0"), ("t1", D3, "w1"),
                ])
                for rid, tile in (("w0", "t0"), ("w1", "t1")):
                    assert got[rid]["served_from"] == "warm"
                    assert _batch_stamp(got[rid])[1] == 2
                    assert _sig(got[rid]) == base[(tile, D3)][0], rid
                # mixed cache-hit/miss group: t0@D1 re-requested (the
                # result cache answers; the member leaves the
                # rendezvous) alongside t2@D3 (a real warm solve that
                # launches without the departed peer).
                got = _submit_group(svc, [
                    ("t0", D1, "m0"), ("t2", D3, "m1"),
                ])
                assert got["m0"]["served_from"] == "cache"
                assert _batch_stamp(got["m0"])[1] == 2
                assert _sig(got["m0"]) == base[("t0", D1)][0]
                assert got["m1"]["served_from"] == "warm"
                assert _batch_stamp(got["m1"])[1] == 2
                assert _sig(got["m1"]) == base[("t2", D3)][0]
            finally:
                svc.close()

    def test_alternative_partition_and_mid_batch_poison(self, tmp_path):
        """The complementary partition {t0,t2} + {t1} matches the same
        baselines; then a poison member errors ALONE — its batch peer's
        answer stays bit-identical and the service survives."""
        base = {}
        for t in ("t0", "t1", "t2"):
            sess = _tile(tmp_path, f"solo{t}", self.SEEDS[t])
            for d in (D1, D3):
                base[(t, d)] = _sig(sess.serve(d))

        with telemetry.use(MetricsRegistry()) as reg:
            svc, _ = _real_service(
                tmp_path, "p2", [self.SEEDS[t] for t in
                                 ("t0", "t1", "t2")],
            )
            svc.start()
            try:
                got = _submit_group(svc, [
                    ("t0", D1, "c0"), ("t2", D1, "c2"),
                ])
                got.update(_submit_group(svc, [("t1", D1, "c1")]))
                assert _batch_stamp(got["c0"])[1] == 2
                assert _batch_stamp(got["c2"])[1] == 2
                assert _batch_stamp(got["c1"]) == (None, None)
                for rid, tile in (("c0", "t0"), ("c1", "t1"),
                                  ("c2", "t2")):
                    assert _sig(got[rid]) == base[(tile, D1)], rid
                # Poison exactly one member of the next group: the
                # fault scripts by call number, so WHICH member dies is
                # scheduling-dependent — the contract is that exactly
                # one errors and the survivor stays bit-identical.
                faults.script("serve.solve", "1", POISON)
                got = _submit_group(svc, [
                    ("t0", D3, "x0"), ("t2", D3, "x2"),
                ])
                by_status = {b["status"] for b in got.values()}
                assert by_status == {"ok", "error"}
                for rid, tile in (("x0", "t0"), ("x2", "t2")):
                    assert _batch_stamp(got[rid])[1] == 2
                    if got[rid]["status"] == "ok":
                        assert got[rid]["served_from"] == "warm"
                        assert _sig(got[rid]) == base[(tile, D3)], rid
                assert reg.value("kafka_serve_errors_total") == 1
                faults.reset()
                # The daemon survives: the next request is fine.
                got = _submit_group(svc, [("t1", D3, "after")])
                assert got["after"]["status"] == "ok"
                assert _sig(got["after"]) == base[("t1", D3)]
            finally:
                svc.close()


# ---------------------------------------------------------------------------
# AOT restart contract (two daemon processes over one compile cache)
# ---------------------------------------------------------------------------

def _sum_counter(metrics, name):
    series = (metrics.get(name) or {}).get("series") or []
    return sum(s.get("value") or 0 for s in series)


class TestAOTWarmRestart:
    def test_second_start_serves_first_request_with_zero_misses(
            self, tmp_path):
        """The AOT acceptance pin: daemon start #1 AOT-compiles the
        declared buckets into --compile-cache-dir and serves a cold
        request; start #2 over a FRESH serve root + checkpoint chain
        (same shapes, warm cache) re-solves the same date with zero
        kafka_compile_cache_misses_total — every lowering is a disk
        hit, and the answers agree bit-for-bit."""
        cache = tmp_path / "xla_cache"
        date = synthetic_dates(DEFAULT_BASE_DATE, 8, 2)[0]

        def run(tag):
            root = tmp_path / f"root_{tag}"
            tele = tmp_path / f"tele_{tag}"
            root.mkdir()
            submit_request(str(root), {
                "tile": "tile0", "date": date.isoformat(),
                "request_id": f"req-{tag}",
            })
            proc = subprocess.run(
                [sys.executable, "-m", "kafka_tpu.cli.kafka_serve",
                 "--root", str(root), "--tiles", "1",
                 "--operator", "identity", "--ny", "8", "--nx", "8",
                 "--days", "8", "--step", "4", "--obs-every", "2",
                 "--compile-cache-dir", str(cache),
                 "--telemetry-dir", str(tele),
                 "--poll-interval-s", "0.02",
                 "--exit-when-idle", "--idle-grace-s", "0.3"],
                env=_subprocess_env(), cwd=REPO_ROOT,
                capture_output=True, text=True, timeout=600,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            summary = json.loads(proc.stdout.strip().splitlines()[-1])
            assert summary["errors"] == 0
            got = read_response(str(root), f"req-{tag}")
            assert got is not None and got["status"] == "ok"
            with open(tele / "metrics.json") as f:
                metrics = json.load(f)
            return got, metrics

        got1, m1 = run("one")
        assert got1["served_from"] == "cold"
        # Start #1 pays the real compiles (cold disk cache).
        assert _sum_counter(m1, "kafka_compile_cache_misses_total") > 0

        got2, m2 = run("two")
        assert got2["served_from"] == "cold"
        assert got2["x_sha256"] == got1["x_sha256"]
        assert _sum_counter(m2, "kafka_compile_cache_misses_total") == 0
        assert _sum_counter(m2, "kafka_compile_cache_hits_total") > 0
