"""Multi-sensor S2+S1 joint assimilation: composite date stream, shared
11-parameter state, per-sensor operators (obsops.joint, io.multi)."""

import datetime

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_tpu.engine import KalmanFilter
from kafka_tpu.engine.priors import JOINT_PARAMETER_LIST, joint_prior
from kafka_tpu.io.multi import CompositeObservations
from kafka_tpu.obsops.joint import (
    ProsailJointOperator,
    WCMJointOperator,
    joint_state_bounds,
)
from kafka_tpu.obsops.wcm import WCMAux, WCM_PARAMETERS, wcm_sigma0
from kafka_tpu.testing import MemoryOutput, SyntheticObservations


def day(i, hour=0):
    return datetime.datetime(2017, 7, 1 + i, hour)


class TestJointOperators:
    def test_prosail_joint_matches_base_and_ignores_sm(self):
        from kafka_tpu.obsops.prosail import ProsailAux, ProsailOperator

        op = ProsailJointOperator()
        base = ProsailOperator()
        aux = ProsailAux(
            sza=jnp.asarray(30.0), vza=jnp.asarray(5.0),
            raa=jnp.asarray(50.0),
        )
        x10 = np.asarray(joint_prior().prior.mean)[:10]
        for sm in (0.05, 0.3, 0.55):
            x11 = jnp.asarray(np.concatenate([x10, [sm]]), jnp.float32)
            brf = op.forward_pixel(aux, x11)
            np.testing.assert_allclose(
                np.asarray(brf),
                np.asarray(base.forward_pixel(aux, jnp.asarray(x10))),
                atol=1e-6,
            )
        # zero Jacobian w.r.t. soil moisture
        lin = op.linearize(aux, jnp.asarray(
            np.concatenate([x10, [0.3]]), jnp.float32)[None, :])
        assert np.abs(np.asarray(lin.jac)[:, 0, 10]).max() == 0.0

    def test_wcm_joint_decodes_physical_lai(self):
        op = WCMJointOperator()
        lai, sm, theta = 3.0, 0.3, 35.0
        x = np.zeros(11, np.float32)
        x[6] = np.exp(-lai / 2.0)
        x[10] = sm
        out = op.forward_pixel(
            WCMAux(theta_deg=jnp.asarray(theta)), jnp.asarray(x)
        )
        for bi, pol in enumerate(("VV", "VH")):
            expect = float(wcm_sigma0(
                jnp.asarray(lai), jnp.asarray(sm), jnp.asarray(theta),
                WCM_PARAMETERS[pol],
            ))
            np.testing.assert_allclose(float(out[bi]), expect, rtol=1e-5)

    def test_wcm_joint_jacobian_couples_lai_and_sm_only(self):
        op = WCMJointOperator()
        x = np.full(11, 0.5, np.float32)
        x[6] = np.exp(-1.5)
        x[10] = 0.25
        lin = op.linearize(
            WCMAux(theta_deg=jnp.asarray(np.full(1, 35.0, np.float32))),
            jnp.asarray(x)[None, :],
        )
        jac = np.asarray(lin.jac)[:, 0]  # (2, 11)
        touched = np.abs(jac).max(axis=0) > 0
        assert touched[6] and touched[10]
        assert not touched[[0, 1, 2, 3, 4, 5, 7, 8, 9]].any()


class TestCompositeObservations:
    def _sources(self):
        op = ProsailJointOperator()
        truth = np.zeros((4, 4, 11), np.float32)
        a = SyntheticObservations(
            dates=[day(1), day(3)], operator=op,
            truth_fn=lambda d: truth, sigma=0.05, seed=0,
        )
        b = SyntheticObservations(
            dates=[day(2), day(3)], operator=op,
            truth_fn=lambda d: truth, sigma=0.05, seed=1,
        )
        return a, b

    def test_union_dates_and_dispatch(self):
        a, b = self._sources()
        comp = CompositeObservations([a, b])
        assert len(comp.dates) == 4  # day3 duplicated -> nudged, kept
        assert comp.dates[0] == day(1)
        # the nudged duplicate is 1 s after the original
        dupes = [d for d in comp.dates if d.day == 4]
        assert len(dupes) == 2
        assert (dupes[1] - dupes[0]).total_seconds() == pytest.approx(2.0)

    def test_bands_per_observation_follows_owner(self):
        a, b = self._sources()
        comp = CompositeObservations([a, b])
        assert all(v == a.bands_per_observation[a.dates[0]]
                   for v in comp.bands_per_observation.values())


class TestJointEndToEnd:
    def test_s1_dates_constrain_soil_moisture(self):
        """A joint run where S2 dates see reflectance and S1 dates see
        backscatter: soil moisture must move from the prior (0.25) toward
        the SAR truth (0.4), and its posterior information must exceed
        the optical-only run's (which cannot observe SM at all)."""
        ny = nx = 8
        mask = np.ones((ny, nx), bool)
        prior = joint_prior()
        truth = np.zeros((ny, nx, 11), np.float32)
        truth[:] = np.asarray(prior.prior.mean)
        truth[..., 6] = np.exp(-3.0 / 2.0)   # LAI 3
        truth[..., 10] = 0.4                 # SAR-visible soil moisture

        s2_op = ProsailJointOperator()
        wcm_op = WCMJointOperator()
        theta = jnp.asarray(np.full(64, 35.0, np.float32))

        def build(with_s1):
            s2 = SyntheticObservations(
                dates=[day(1), day(5)], operator=s2_op,
                truth_fn=lambda d: truth, sigma=0.005, seed=3,
            )
            sources = [s2]
            if with_s1:
                s1 = SyntheticObservations(
                    dates=[day(2), day(4)], operator=wcm_op,
                    truth_fn=lambda d: truth, sigma=0.003, seed=4,
                    aux_fn=lambda d, g: WCMAux(theta_deg=theta),
                )
                sources.append(s1)
            obs = CompositeObservations(sources)
            kf = KalmanFilter(
                obs, MemoryOutput(), mask, JOINT_PARAMETER_LIST,
                state_propagation=None, prior=None, pad_multiple=64,
                solver_options={"relaxation": 0.7},
            )
            x0, p_inv0 = prior.process_prior(None, kf.gather)
            x_a, _, p_inv_a = kf.run([day(0), day(6)], x0, None, p_inv0)
            return np.asarray(x_a), np.asarray(p_inv_a)

        x_joint, p_inv_joint = build(with_s1=True)
        x_opt, p_inv_opt = build(with_s1=False)

        sm_joint = x_joint[:64, 10]
        sm_opt = x_opt[:64, 10]
        # Optical-only leaves SM at its prior; SAR pulls it to ~0.4.
        np.testing.assert_allclose(sm_opt, 0.25, atol=1e-3)
        assert np.abs(sm_joint - 0.4).mean() < 0.05
        # SAR adds information on the SM diagonal.
        assert (p_inv_joint[:64, 10, 10] > 2 * p_inv_opt[:64, 10, 10]).all()
        # And LAI stays optically constrained in both.
        lai_joint = -2 * np.log(np.clip(x_joint[:64, 6], 1e-6, 1))
        assert np.abs(lai_joint - 3.0).mean() < 0.35
