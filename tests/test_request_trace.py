"""End-to-end request tracing (ISSUE 14): fleet-wide span propagation,
tail-latency attribution, and slow-request forensics.

Acceptance pins:

- every span recorded under ``tracing.push(request_id=...)`` carries
  the id, and the serving path records a full non-overlapping phase
  breakdown (admission_wait / queue_wait / resume / solve / dump on a
  replica, + failover / forward / relay through the router) whose sum
  attributes >=95% of the server-side wall time;
- both router and replica write one ``request_log.jsonl`` wide event
  per admitted request; ``tools/trace_report.py`` ranks the slowest,
  resolves p99 to a concrete request id, and flags unattributed wall
  time;
- ``aggregate.stitch_traces(request_id=...)`` stitches ONE request's
  cross-process waterfall with flow events across the forward/relay
  hops;
- chaos: a 3-replica fleet behind kafka-route, the tile0 owner
  SIGKILLed mid-request — the stitched per-request trace contains
  router, victim and survivor tracks with a ``route_failover`` span,
  and trace_report attributes the added tail latency to the failover
  phase;
- the ``kafka_engine_device_reads_total == dispatches`` invariant is
  unchanged with request tracing active.

All tier-1 / CPU.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from kafka_tpu import telemetry
from kafka_tpu.serve import (
    AssimilationService,
    HashRing,
    ServeDaemon,
    TileRouter,
    TileSession,
    make_synthetic_tile,
    read_response,
    submit_request,
    synthetic_dates,
)
from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
from kafka_tpu.telemetry import MetricsRegistry, request_log, tracing
from kafka_tpu.telemetry.aggregate import stitch_traces
from kafka_tpu.telemetry.httpd import TelemetryHTTPd
from kafka_tpu.telemetry.tracing import trace_span

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DATES = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)


class StubSession:
    """Duck-typed session reporting honest phase timings."""

    def __init__(self, name, solve_s=0.02, fail=None):
        self.name = name
        self.solve_s = solve_s
        self.fail = fail
        self.serves = 0

    def serve(self, date):
        self.serves += 1
        if self.fail is not None:
            raise self.fail
        t0 = time.perf_counter()
        time.sleep(self.solve_s)
        return {
            "status": "ok", "tile": self.name,
            "date": date.isoformat(), "served_from": "warm",
            "x_sha256": f"stub-{self.name}",
            "trace_phases": {
                "resume_ms": 0.0,
                "solve_ms": (time.perf_counter() - t0) * 1e3,
            },
        }


def wait_response(root, rid, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = read_response(root, rid)
        if got is not None:
            return got
        time.sleep(0.01)
    return None


# ---------------------------------------------------------------------------
# trace context: request_id rides every span
# ---------------------------------------------------------------------------

class TestRequestContext:
    def test_spans_under_push_carry_request_id(self):
        with telemetry.use(MetricsRegistry()) as reg:
            with tracing.push(run_id="r", request_id="rq1"):
                with trace_span("outer"):
                    with trace_span("inner"):
                        pass
            with trace_span("unrelated"):
                pass
            events = reg.trace.to_chrome()["traceEvents"]
        spans = {e["name"]: e for e in events if e.get("ph") == "X"}
        assert spans["outer"]["args"]["request_id"] == "rq1"
        assert spans["inner"]["args"]["request_id"] == "rq1"
        assert "request_id" not in spans["unrelated"]["args"]

    def test_push_overrides_only_given_fields(self):
        with tracing.push(run_id="r", chunk_id="c"):
            with tracing.push(request_id="rq2") as ctx:
                assert ctx.run_id == "r"
                assert ctx.chunk_id == "c"
                assert ctx.request_id == "rq2"


# ---------------------------------------------------------------------------
# request_log: wide events, ring, rotation, read side
# ---------------------------------------------------------------------------

class TestRequestLog:
    def test_record_lands_in_file_ring_and_counter(self, tmp_path):
        with telemetry.use(MetricsRegistry(str(tmp_path))) as reg:
            rec = request_log.record(request_log.build_record(
                "serve", "rqA", status="ok", e2e_ms=12.5,
                phases={"solve_ms": 12.0}, tile="t",
                served_from="warm",
            ))
            assert rec["e2e_ms"] == 12.5
            records, torn = request_log.load_records(str(tmp_path))
            assert torn == 0
            assert [r["request_id"] for r in records] == ["rqA"]
            assert reg.value("kafka_request_log_records_total",
                             role="serve") == 1
            view = request_log.requestz(8)
            assert view["recent"][0]["request_id"] == "rqA"
            assert view["inflight"] == []

    def test_inflight_note_and_clear_on_record(self):
        with telemetry.use(MetricsRegistry()):
            request_log.note_inflight("rqB", tile="t", stage="queued")
            request_log.note_inflight("rqB", stage="solving")
            view = request_log.requestz(8)
            assert view["inflight"][0]["stage"] == "solving"
            request_log.record(request_log.build_record(
                "serve", "rqB", status="ok", e2e_ms=1.0,
            ))
            assert request_log.requestz(8)["inflight"] == []

    def test_rotation_bounds_the_log(self, tmp_path, monkeypatch):
        monkeypatch.setattr(request_log, "ROTATE_BYTES", 400)
        with telemetry.use(MetricsRegistry(str(tmp_path))):
            for i in range(40):
                request_log.record(request_log.build_record(
                    "serve", f"rq{i:03d}", status="ok", e2e_ms=1.0,
                    phases={"solve_ms": 1.0}, tile="t" * 10,
                ))
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.startswith(request_log.LOG_FILENAME))
        assert f"{request_log.LOG_FILENAME}.1" in names
        # keep-N enforced.
        assert f"{request_log.LOG_FILENAME}." \
               f"{request_log.KEEP_SEGMENTS + 1}" not in names
        # ...and the read side walks the segments oldest-first: order
        # is preserved across rotation boundaries for surviving rows.
        records, _ = request_log.load_records(str(tmp_path))
        ids = [r["request_id"] for r in records]
        assert ids == sorted(ids)

    def test_torn_tail_skipped(self, tmp_path):
        path = tmp_path / request_log.LOG_FILENAME
        path.write_text(
            json.dumps({"request_id": "ok1", "e2e_ms": 5,
                        "phases": {"solve_ms": 5}}) + "\n"
            + '{"request_id": "torn'
        )
        records, torn = request_log.load_records(str(tmp_path))
        assert [r["request_id"] for r in records] == ["ok1"]
        assert torn == 1

    def test_attributed_fraction(self):
        assert request_log.attributed_fraction(
            {"e2e_ms": 100.0, "phases": {"a_ms": 60.0, "b_ms": 39.0}}
        ) == pytest.approx(0.99)
        assert request_log.attributed_fraction(
            {"e2e_ms": 0.0, "phases": {"a_ms": 1.0}}) is None
        assert request_log.attributed_fraction({"phases": {}}) is None

    def test_is_covered_fraction_bar_and_noise_floor(self):
        # >=95% attributed: covered.
        assert request_log.is_covered(
            {"e2e_ms": 100.0, "phases": {"a_ms": 96.0}}) is True
        # 50% attributed with a 50 ms hole: a finding.
        assert request_log.is_covered(
            {"e2e_ms": 100.0, "phases": {"a_ms": 50.0}}) is False
        # A sub-ms cache hit with microseconds of glue: the fraction
        # fails but the absolute remainder is noise, not latency.
        assert request_log.is_covered(
            {"e2e_ms": 0.7, "phases": {"a_ms": 0.65}}) is True
        # No usable timing: unknown.
        assert request_log.is_covered({"phases": {}}) is None


# ---------------------------------------------------------------------------
# service: the replica-side waterfall
# ---------------------------------------------------------------------------

class TestServiceTrace:
    def test_ok_response_carries_full_attribution(self, tmp_path):
        with telemetry.use(MetricsRegistry(str(tmp_path / "tel"))) as reg:
            svc = AssimilationService(
                {"t": StubSession("t", solve_s=0.05)}, str(tmp_path),
            ).start()
            try:
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "rq1"})
                got = svc.result("rq1", timeout_s=30)
            finally:
                svc.close()
            trace = got["trace"]
            assert trace["request_id"] == "rq1"
            for key in ("admission_wait_ms", "queue_wait_ms",
                        "resume_ms", "solve_ms", "dump_ms"):
                assert key in trace["phases"], key
            assert trace["e2e_ms"] > 0
            # The named phases explain >=95% of the server-side wall.
            assert request_log.attributed_fraction(trace) >= 0.95
            # The journal entry carries the admission stamp (trace
            # continuation across replay).
            with open(svc.journal.journal_path) as f:
                entry = json.loads(f.readline())
            assert entry["request_id"] == "rq1"
            assert entry["admitted_ts"] == pytest.approx(
                trace["admitted_ts"])
            # The wide event matches the response's attribution.
            records, _ = request_log.load_records(
                str(tmp_path / "tel"))
            rec = [r for r in records if r["request_id"] == "rq1"][0]
            assert rec["role"] == "serve"
            assert rec["status"] == "ok"
            assert rec["served_from"] == "warm"
            assert rec["phases"] == trace["phases"]
            # ...and the waterfall spans carry the request id.
            spans = [e for e in reg.trace.to_chrome()["traceEvents"]
                     if e.get("ph") == "X"
                     and e["args"].get("request_id") == "rq1"]
            names = {e["name"] for e in spans}
            assert {"serve_admit", "queue_wait"} <= names

    def test_error_and_cancelled_requests_get_rows(self, tmp_path):
        with telemetry.use(MetricsRegistry(str(tmp_path / "tel"))):
            svc = AssimilationService(
                {"bad": StubSession("bad", fail=ValueError("boom")),
                 "ok": StubSession("ok", solve_s=0.2)},
                str(tmp_path),
            ).start()
            try:
                # Queue a slow request, then one with an already-tiny
                # deadline behind it (cancelled at dequeue), then the
                # poison one.
                svc.submit({"tile": "ok", "date": "2017-07-05",
                            "request_id": "slow"})
                svc.submit({"tile": "ok", "date": "2017-07-07",
                            "request_id": "late", "deadline_s": 0.01})
                svc.submit({"tile": "bad", "date": "2017-07-05",
                            "request_id": "err"})
                for rid in ("slow", "late", "err"):
                    assert svc.result(rid, timeout_s=30) is not None
            finally:
                svc.close()
            records, _ = request_log.load_records(
                str(tmp_path / "tel"))
            by_id = {r["request_id"]: r for r in records}
            assert by_id["slow"]["status"] == "ok"
            assert by_id["late"]["status"] == "cancelled"
            assert by_id["err"]["status"] == "error"
            # Every admitted request has a row with wait attribution.
            for rid in ("late", "err"):
                assert "admission_wait_ms" in by_id[rid]["phases"]
                assert by_id[rid]["e2e_ms"] is not None

    def test_cache_hit_served_and_recorded(self, tmp_path):
        with telemetry.use(MetricsRegistry(str(tmp_path / "tel"))):
            svc = AssimilationService(
                {"t": StubSession("t")}, str(tmp_path),
            ).start()
            try:
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "c1"})
                assert svc.result("c1", timeout_s=30)["status"] == "ok"
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "c2"})
                got = svc.result("c2", timeout_s=30)
            finally:
                svc.close()
            assert got["served_from"] == "cache"
            assert got["trace"]["request_id"] == "c2"
            records, _ = request_log.load_records(
                str(tmp_path / "tel"))
            rec = [r for r in records if r["request_id"] == "c2"][0]
            assert rec["served_from"] == "cache"

    def test_replay_continues_trace_with_replayed_span(self, tmp_path):
        """Satellite 1: a journal-replayed request keeps its id (the
        trace continues) and shows a visible `replayed` span — not a
        fresh waterfall."""
        with telemetry.use(MetricsRegistry(str(tmp_path / "tel"))) as reg:
            svc = AssimilationService(
                {"t": StubSession("t")}, str(tmp_path),
            )
            # A journaled-but-unanswered request (the crash leftover).
            svc.journal.record({
                "request_id": "rep1", "tile": "t",
                "date": "2017-07-05", "deadline_s": None,
                "submitted_ts": time.time() - 5.0,
                "admitted_ts": time.time() - 5.0,
            })
            svc.start()
            try:
                got = svc.result("rep1", timeout_s=30)
            finally:
                svc.close()
            assert got["status"] == "ok"
            assert got["trace"]["request_id"] == "rep1"
            assert got["trace"]["replayed"] is True
            spans = [e for e in reg.trace.to_chrome()["traceEvents"]
                     if e.get("ph") == "X" and e["name"] == "replayed"]
            assert spans and \
                spans[0]["args"]["request_id"] == "rep1"
            records, _ = request_log.load_records(
                str(tmp_path / "tel"))
            rec = [r for r in records if r["request_id"] == "rep1"][0]
            assert rec["replayed"] is True


# ---------------------------------------------------------------------------
# session phases + the device-reads invariant under tracing
# ---------------------------------------------------------------------------

class TestSessionPhases:
    def test_serve_reports_resume_solve_dump(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            sess = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ckpt"), seed=0))
            body = sess.serve(DATES[-1])
        phases = body["trace_phases"]
        assert set(phases) == {"resume_ms", "solve_ms", "dump_ms"}
        assert phases["solve_ms"] > 0

    def test_device_reads_invariant_with_request_tracing(
            self, tmp_path):
        """Zero new device->host transfers: serving under a request
        trace context performs exactly the reads an untraced serve
        does — the per-request attribution is host-side arithmetic on
        stamps the path already takes."""
        with telemetry.use(MetricsRegistry()) as reg:
            sess = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ck_traced"), seed=0))
            with tracing.push(run_id="r", request_id="rq-dev"):
                traced = sess.serve(DATES[-1])
            reads_traced = reg.value(
                "kafka_engine_device_reads_total")
        with telemetry.use(MetricsRegistry()) as reg:
            sess = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ck_plain"), seed=0))
            plain = sess.serve(DATES[-1])
            reads_plain = reg.value(
                "kafka_engine_device_reads_total")
        assert reads_traced == reads_plain
        assert reads_traced and reads_traced > 0
        assert traced["x_sha256"] == plain["x_sha256"]


# ---------------------------------------------------------------------------
# /requestz endpoint
# ---------------------------------------------------------------------------

class TestRequestzEndpoint:
    def test_json_and_text_views(self):
        with telemetry.use(MetricsRegistry()) as reg:
            request_log.record(request_log.build_record(
                "serve", "rq9", status="ok", e2e_ms=12.5,
                phases={"solve_ms": 12.0}, tile="t",
                served_from="warm",
            ))
            request_log.note_inflight("rq10", tile="t", stage="queued")
            httpd = TelemetryHTTPd(port=0, registry=reg,
                                   role="serve").start()
            try:
                with urllib.request.urlopen(
                        f"{httpd.url}/requestz?json=1",
                        timeout=5) as resp:
                    payload = json.loads(resp.read().decode())
                assert payload["recent"][0]["request_id"] == "rq9"
                assert payload["inflight"][0]["request_id"] == "rq10"
                with urllib.request.urlopen(
                        f"{httpd.url}/requestz", timeout=5) as resp:
                    text = resp.read().decode()
                assert "rq9" in text and "INFLIGHT rq10" in text
                assert "worst=solve_ms" in text
                # The index page advertises it.
                with urllib.request.urlopen(
                        f"{httpd.url}/", timeout=5) as resp:
                    assert "/requestz" in json.loads(
                        resp.read().decode())["endpoints"]
            finally:
                httpd.close()

    def test_bad_n_is_400(self):
        with telemetry.use(MetricsRegistry()) as reg:
            httpd = TelemetryHTTPd(port=0, registry=reg).start()
            try:
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(
                        f"{httpd.url}/requestz?n=nope", timeout=5)
                assert exc.value.code == 400
            finally:
                httpd.close()


# ---------------------------------------------------------------------------
# per-request stitching with flow events
# ---------------------------------------------------------------------------

def _fragment(root, sub, epoch, spans):
    """One per-process trace.json fragment: spans = (name, ts_us, dur,
    args)."""
    events = [{"name": "process_name", "ph": "M", "ts": 0.0,
               "pid": 7, "tid": 0, "args": {"name": "kafka_tpu"}},
              {"name": "thread_name", "ph": "M", "ts": 0.0,
               "pid": 7, "tid": 1, "args": {"name": "serve"}}]
    for name, ts, dur, args in spans:
        events.append({"name": name, "cat": "span", "ph": "X",
                       "ts": ts, "dur": dur, "pid": 7, "tid": 1,
                       "args": args})
    os.makedirs(os.path.join(root, sub), exist_ok=True)
    with open(os.path.join(root, sub, "trace.json"), "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"epoch_unix_s": epoch,
                                 "run_ids": ["r"]}}, f)


class TestStitchByRequest:
    def test_filters_to_one_request_and_adds_flows(self, tmp_path):
        root = str(tmp_path)
        _fragment(root, "router", 100.0, [
            ("route_forward", 0.0, 50.0, {"request_id": "rq1"}),
            ("route_relay", 5000.0, 30.0, {"request_id": "rq1"}),
            ("route_forward", 100.0, 10.0, {"request_id": "other"}),
        ])
        _fragment(root, "rep0", 100.001, [
            ("serve_admit", 500.0, 20.0, {"request_id": "rq1"}),
            ("queue_wait", 600.0, 100.0, {"request_id": "rq1"}),
            ("serve_solve", 800.0, 2000.0, {"request_id": "rq1"}),
        ])
        # A process that never saw rq1 contributes no track.
        _fragment(root, "rep1", 100.0, [
            ("serve_admit", 0.0, 5.0, {"request_id": "other"}),
        ])
        doc = stitch_traces(root, request_id="rq1")
        assert doc["otherData"]["request_id_filter"] == "rq1"
        assert len(doc["otherData"]["sources"]) == 2
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert all(e["args"]["request_id"] == "rq1" for e in xs)
        assert len(xs) == 5
        # Two pid tracks, flow arrows across the hops.
        assert len({e["pid"] for e in xs}) == 2
        flows = [e for e in doc["traceEvents"]
                 if e.get("ph") in ("s", "f")]
        assert flows, "no flow events across the process hops"
        starts = [e for e in flows if e["ph"] == "s"]
        ends = [e for e in flows if e["ph"] == "f"]
        assert len(starts) == len(ends)
        for s, e in zip(starts, ends):
            assert s["id"] == e["id"]
            assert s["pid"] != e["pid"]
        # Every event is a well-formed Chrome trace event.
        for e in doc["traceEvents"]:
            assert "name" in e and "ph" in e and "pid" in e

    def test_no_match_yields_empty_trace(self, tmp_path):
        _fragment(str(tmp_path), "router", 100.0, [
            ("route_forward", 0.0, 50.0, {"request_id": "other"}),
        ])
        doc = stitch_traces(str(tmp_path), request_id="ghost")
        assert doc["otherData"]["sources"] == []
        assert doc["traceEvents"] == []


# ---------------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------------

def _write_log(dirpath, rows):
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, request_log.LOG_FILENAME),
              "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


class TestTraceReport:
    def _seed(self, root):
        _write_log(os.path.join(root, "router"), [
            {"ts": 3.0, "role": "route", "request_id": "slow1",
             "status": "ok", "tile": "tile0", "served_from": "warm",
             "replica": "rep1", "e2e_ms": 5000.0,
             "phases": {"admission_wait_ms": 5.0,
                        "failover_ms": 4200.0, "forward_ms": 10.0,
                        "queue_wait_ms": 5.0, "resume_ms": 100.0,
                        "solve_ms": 600.0, "dump_ms": 5.0,
                        "relay_ms": 50.0},
             "reroutes": [{"reason": "dead", "replica": "rep0",
                           "held_ms": 4200.0}]},
            {"ts": 1.0, "role": "route", "request_id": "fast1",
             "status": "ok", "tile": "tile1", "served_from": "warm",
             "replica": "rep0", "e2e_ms": 50.0,
             "phases": {"admission_wait_ms": 2.0, "forward_ms": 3.0,
                        "queue_wait_ms": 1.0, "resume_ms": 4.0,
                        "solve_ms": 38.0, "dump_ms": 1.0,
                        "relay_ms": 1.0}},
        ])
        _write_log(os.path.join(root, "rep1"), [
            # The replica's own record of slow1: the router's merged
            # record must win (it has the full e2e).
            {"ts": 2.5, "role": "serve", "request_id": "slow1",
             "status": "ok", "tile": "tile0", "served_from": "warm",
             "e2e_ms": 720.0,
             "phases": {"queue_wait_ms": 5.0, "resume_ms": 100.0,
                        "solve_ms": 600.0, "dump_ms": 5.0},
             "solver_health": {"quarantined": 0}},
            {"ts": 2.0, "role": "serve", "request_id": "gap1",
             "status": "ok", "tile": "tile1", "served_from": "warm",
             "e2e_ms": 100.0, "phases": {"solve_ms": 50.0}},
        ])

    def test_report_merges_ranks_and_flags(self, tmp_path):
        from tools.trace_report import build_report

        self._seed(str(tmp_path))
        report = build_report(str(tmp_path), slowest=5)
        assert report["requests_total"] == 3
        assert report["by_status"] == {"ok": 3}
        slowest = report["slowest"]
        assert slowest[0]["request_id"] == "slow1"
        # The router record won the merge and carries the failover
        # attribution + the replica's solver_health backfill.
        assert slowest[0]["role"] == "route"
        assert slowest[0]["phases"]["failover_ms"] == 4200.0
        assert slowest[0]["solver_health"] == {"quarantined": 0}
        assert slowest[0]["coverage"] >= 0.99
        # The unattributed check catches gap1 (50% attributed).
        assert [u["request_id"] for u in report["unattributed"]] == \
            ["gap1"]
        # p99 resolves to a real request id in a real histogram
        # bucket.
        p99 = report["exemplars"]["p99"]
        assert p99["request_id"] == "slow1"
        assert p99["value_ms"] == 5000.0
        assert p99["bucket_le_ms"] == 5000.0
        assert "slow1" in p99["bucket_request_ids"]

    def test_cli_exit_codes_and_json(self, tmp_path, capsys):
        from tools.trace_report import main

        self._seed(str(tmp_path))
        assert main([str(tmp_path), "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["requests_total"] == 3
        # --unattributed is a check: exit 1 while gap1 is below bar.
        assert main([str(tmp_path), "--unattributed"]) == 1
        capsys.readouterr()
        assert main([str(tmp_path), "--unattributed",
                     "--coverage", "0.4"]) == 0
        capsys.readouterr()
        # Single-request detail; unknown id and missing root are usage
        # errors.
        assert main([str(tmp_path), "--request", "slow1"]) == 0
        out = capsys.readouterr().out
        assert "failover=4200.0ms" in out
        assert "reroute: rep0 (dead" in out
        assert main([str(tmp_path), "--request", "nope"]) == 2
        assert main([str(tmp_path / "missing")]) == 2

    def test_stitch_flag_writes_request_trace(self, tmp_path, capsys):
        from tools.trace_report import main

        self._seed(str(tmp_path))
        _fragment(str(tmp_path), "router", 100.0, [
            ("route_forward", 0.0, 50.0, {"request_id": "slow1"}),
        ])
        _fragment(str(tmp_path), "rep1", 100.0, [
            ("serve_solve", 500.0, 600.0, {"request_id": "slow1"}),
        ])
        out_path = str(tmp_path / "req.json")
        assert main([str(tmp_path), "--request", "slow1",
                     "--stitch", out_path, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["stitched_trace"]["path"] == out_path
        with open(out_path) as f:
            doc = json.load(f)
        assert len(doc["otherData"]["sources"]) == 2
        # --stitch without --request is usage.
        assert main([str(tmp_path), "--stitch", out_path]) == 2


# ---------------------------------------------------------------------------
# loadgen coverage rows
# ---------------------------------------------------------------------------

class TestLoadgenCoverage:
    def test_rows_emitted_from_server_traces(self, tmp_path):
        from tools.loadgen import _Target, run_load

        with telemetry.use(MetricsRegistry()):
            svc = AssimilationService(
                {"t": StubSession("t", solve_s=0.03)}, str(tmp_path),
            ).start()
            try:
                rows = run_load(
                    _Target(service=svc),
                    [{"tile": "t", "date": "2017-07-05"}
                     for _ in range(6)],
                    concurrency=2, timeout_s=60,
                )
            finally:
                svc.close()
        assert rows["serve_ok_total"] == 6
        assert rows["serve_trace_coverage"] == 1.0
        assert rows["serve_slowest_ms"] > 0

    def test_bench_compare_diffs_informationally(self, tmp_path,
                                                 capsys):
        from tools.bench_compare import main as compare

        base = {"serve_trace_coverage": 1.0, "serve_slowest_ms": 40.0}
        old = tmp_path / "old.json"
        new = tmp_path / "new.json"
        old.write_text(json.dumps(base))
        new.write_text(json.dumps({"serve_trace_coverage": 0.8,
                                   "serve_slowest_ms": 90.0}))
        # No gate: exit 0 — but the coverage drop is called out.
        assert compare([str(old), str(new)]) == 0
        out = capsys.readouterr().out
        assert "serve_trace_coverage: 1 -> 0.8" in out
        assert "WARNING serve_trace_coverage dropped" in out
        assert "serve_slowest_ms: 40 -> 90" in out


# ---------------------------------------------------------------------------
# fleet_status surfacing
# ---------------------------------------------------------------------------

class TestFleetStatusRecentRequests:
    def test_render_shows_recent_requests(self, tmp_path):
        from tools.fleet_status import build_view, render

        snap = {
            "schema": 1, "ts": time.time(), "host": "h", "pid": 9,
            "role": "serve", "seq": 1, "interval_s": 2.0,
            "final": False, "run_id": None, "chunk_id": None,
            "health": {"unhealthy": None}, "quality": {}, "perf": {},
            "counters": {}, "gauges": {}, "histograms": {},
            "series_truncated": 0, "crash_dumps": [],
            "status": {"recent_requests": [
                {"request_id": "rq7", "status": "ok",
                 "served_from": "warm", "e2e_ms": 42.0},
            ]},
        }
        with open(tmp_path / "live_h_9.json", "w") as f:
            json.dump(snap, f)
        text = render(build_view(str(tmp_path), ttl_s=60.0))
        assert "recent: rq7(ok,warm,42ms)" in text


# ---------------------------------------------------------------------------
# the chaos acceptance: 3-replica fleet, SIGKILL the tile0 owner
# ---------------------------------------------------------------------------

def _subprocess_env():
    from kafka_tpu.resilience import faults

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KAFKA_TPU_LIVE_INTERVAL_S"] = "0.2"
    env.pop(faults.ENV_VAR, None)
    return env


def _replica_cmd(root, ckpt_root, tel_dir):
    return [
        sys.executable, "-m", "kafka_tpu.cli.kafka_serve",
        "--root", str(root), "--ckpt-root", str(ckpt_root),
        "--tiles", "2", "--operator", "identity",
        "--ny", "16", "--nx", "20", "--days", "40", "--step", "2",
        "--obs-every", "2", "--poll-interval-s", "0.02",
        "--telemetry-dir", str(tel_dir),
    ]


def _router_cmd(front, replicas, fleet_dir, tel_dir):
    spec = ",".join(f"{rid}={root}" for rid, root in replicas.items())
    return [
        sys.executable, "-m", "kafka_tpu.cli.kafka_route",
        "--root", str(front), "--replicas", spec,
        "--fleet-dir", str(fleet_dir), "--ttl-s", "1.0",
        "--refresh-s", "0.2", "--poll-interval-s", "0.02",
        "--telemetry-dir", str(tel_dir),
    ]


def _trace_has_request(path, rid):
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    return any(
        (e.get("args") or {}).get("request_id") == rid
        for e in doc.get("traceEvents") or ()
    )


class TestRequestTracingChaosAcceptance:
    def test_failover_trace_attributes_tail_latency(self, tmp_path):
        """ISSUE 14 acceptance: loadgen against a 3-replica fleet
        behind kafka-route with one SIGKILL mid-request.  Every
        admitted request leaves a request_log row and a stitchable
        per-request trace; the victim request's stitched waterfall
        contains router, victim and survivor tracks with a
        route_failover span; trace_report attributes >=95% of the
        slowest request's wall time to named phases with failover
        dominating; the p99 exemplar resolves to a real request id
        whose stitched trace is a well-formed Chrome trace with >=2
        process tracks."""
        from tools.loadgen import _Target, run_load
        from tools.trace_report import build_report

        env = _subprocess_env()
        tel = tmp_path / "tel"
        ckpt = tmp_path / "ckpt"
        front = str(tmp_path / "front")
        dates = synthetic_dates(DEFAULT_BASE_DATE, 40, 2)
        date = dates[-1]

        replicas = {f"rep{i}": str(tmp_path / f"rep{i}")
                    for i in range(3)}
        victim_rid = HashRing(replicas).owner("tile0")
        procs = {}
        router_proc = None
        try:
            for rid, root in replicas.items():
                procs[rid] = subprocess.Popen(
                    _replica_cmd(root, ckpt, tel / rid), env=env,
                    cwd=REPO_ROOT, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            router_proc = subprocess.Popen(
                _router_cmd(front, replicas, tel, tel / "router"),
                env=env, cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            )
            victim = procs[victim_rid]

            # Wait for the router's first heartbeat before submitting:
            # the victim request's admission_wait must measure inbox
            # wait, not router process boot — failover must be the
            # dominant phase of its breakdown.
            router_tel = tel / "router"
            deadline = time.time() + 300
            while time.time() < deadline:
                if router_tel.is_dir() and any(
                        n.startswith("live_")
                        for n in os.listdir(router_tel)):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("router never published a heartbeat")

            rid = submit_request(front, {
                "tile": "tile0", "date": date.isoformat(),
                "request_id": "victimreq",
            })
            # Kill the owner once (a) it admitted the request
            # (journal), (b) warm state exists (shared checkpoints),
            # and (c) its live-published trace fragment carries the
            # request — the victim track the stitched waterfall needs.
            victim_journal = tmp_path / victim_rid / "requests.jsonl"
            victim_trace = tel / victim_rid / "trace.json"
            ck_dir = ckpt / "ckpt_tile0"
            deadline = time.time() + 300
            while time.time() < deadline:
                if victim.poll() is not None:
                    pytest.fail(
                        f"victim exited rc={victim.returncode} before "
                        "it could be killed"
                    )
                if read_response(front, rid) is not None:
                    pytest.fail("fleet answered before the kill — "
                                "widen the request")
                journal_text = victim_journal.read_text() \
                    if victim_journal.exists() else ""
                if rid in journal_text and ck_dir.is_dir() and any(
                        n.endswith(".npz")
                        for n in os.listdir(ck_dir)) and \
                        _trace_has_request(victim_trace, rid):
                    break
                time.sleep(0.005)
            else:
                pytest.fail("victim never admitted + checkpointed + "
                            "published its trace")
            victim.kill()
            victim.wait(timeout=30)

            got = wait_response(front, rid, timeout_s=300)
            assert got is not None, "re-routed request was lost"
            assert got["status"] == "ok"
            assert got["replica"] != victim_rid
            # The relayed response carries the merged attribution with
            # the failover hop on record.
            trace = got["trace"]
            assert trace["request_id"] == rid
            assert trace["phases"]["failover_ms"] > 0
            assert trace["reroutes"][0]["replica"] == victim_rid
            assert trace["reroutes"][0]["reason"] == "dead"

            # Post-failover load: every request lands, and every
            # server trace attributes >=95% of its wall time.
            plan = [{"tile": f"tile{i % 2}",
                     "date": dates[-1 - (i % 2)].isoformat()}
                    for i in range(6)]
            rows = run_load(_Target(root=front), plan, concurrency=3,
                            timeout_s=300, backoff_budget=8)
            assert rows["serve_ok_total"] == 6
            assert rows["serve_trace_coverage"] == 1.0
            assert rows["serve_slowest_ms"] > 0

            # Clean shutdown so every process dumps its full trace.
            router_proc.send_signal(signal.SIGTERM)
            out, _ = router_proc.communicate(timeout=120)
            assert router_proc.returncode == 0
            for rep_rid, proc in procs.items():
                if rep_rid != victim_rid:
                    proc.send_signal(signal.SIGTERM)
            for rep_rid, proc in procs.items():
                if rep_rid != victim_rid:
                    assert proc.wait(timeout=120) == 0

            # 100% of admitted requests have a router wide event.
            records, torn = request_log.load_records(str(tel))
            assert torn == 0
            route_rows = {r["request_id"]: r for r in records
                          if r["role"] == "route"}
            assert len(route_rows) == 7  # victimreq + 6 loadgen
            assert all(r["status"] == "ok"
                       for r in route_rows.values())

            # trace_report: the slowest request IS the victim, >=95%
            # attributed, failover the dominant phase.
            report = build_report(str(tel), slowest=10)
            slowest = report["slowest"][0]
            assert slowest["request_id"] == rid
            assert slowest["coverage"] >= 0.95
            phases = slowest["phases"]
            assert phases["failover_ms"] == max(phases.values())
            assert report["unattributed"] == []
            assert report["coverage_ok_fraction"] == 1.0

            # The p99 exemplar resolves to a real request whose
            # stitched trace is a well-formed Chrome trace with >=2
            # process tracks.
            p99 = report["exemplars"]["p99"]
            assert p99["request_id"] in route_rows
            doc = stitch_traces(str(tel),
                                request_id=p99["request_id"])
            assert len(doc["otherData"]["sources"]) >= 2
            for e in doc["traceEvents"]:
                assert "name" in e and "ph" in e and "pid" in e

            # The victim request's waterfall: router + victim +
            # survivor tracks, with the route_failover span.
            doc = stitch_traces(str(tel), request_id=rid)
            src_dirs = {os.path.dirname(s["path"])
                        for s in doc["otherData"]["sources"]}
            assert "router" in src_dirs
            assert victim_rid in src_dirs, (
                "victim track missing — live trace persistence "
                f"failed (sources: {sorted(src_dirs)})"
            )
            assert len(src_dirs) >= 3
            span_names = {e["name"] for e in doc["traceEvents"]
                          if e.get("ph") == "X"}
            assert "route_failover" in span_names
            assert "route_forward" in span_names
            flows = [e for e in doc["traceEvents"]
                     if e.get("ph") in ("s", "f")]
            assert flows, "no flow events across the failover hops"
        finally:
            for proc in list(procs.values()) + [router_proc]:
                if proc is not None and proc.poll() is None:
                    proc.kill()
