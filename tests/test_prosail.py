"""Physics-invariant tests for the differentiable PROSAIL-family operator.

The reference's PROSAIL path is only exercised through unpicklable GP
emulators; these tests pin the *physics* of the in-repo replacement:
bounds, limits (bare soil / dense canopy), spectral shape (red edge,
chlorophyll absorption), hotspot behavior, and Jacobian finiteness.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_tpu.obsops.prosail import (
    PROSAIL_PARAMETER_LIST,
    ProsailAux,
    ProsailOperator,
    SOIL_DRY,
    SOIL_WET,
    expint_e1,
    inverse_transforms,
    leaf_optics,
)


def make_state(lai=2.0, cab=40.0, n=1.5, ala=57.0, bsoil=1.0, psoil=0.5,
               car=8.0, cbrown=0.05, cw=0.012, cm=0.005):
    """Physical values -> transformed state vector."""
    return jnp.asarray([
        n, np.exp(-cab / 100.0), np.exp(-car / 100.0), cbrown,
        np.exp(-50.0 * cw), np.exp(-100.0 * cm), np.exp(-lai / 2.0),
        ala / 90.0, bsoil, psoil,
    ], jnp.float32)


AUX = ProsailAux(sza=jnp.asarray(30.0), vza=jnp.asarray(5.0),
                 raa=jnp.asarray(90.0))
OP = ProsailOperator()


class TestExpint:
    def test_against_scipy(self):
        from scipy.special import exp1

        x = np.logspace(-3, 1.5, 40)
        got = np.asarray(expint_e1(jnp.asarray(x, jnp.float32)))
        np.testing.assert_allclose(got, exp1(x), rtol=5e-3, atol=1e-6)


class TestLeafOptics:
    def test_energy_conservation(self):
        rho, tau = leaf_optics(
            jnp.asarray(1.5), jnp.asarray(40.0), jnp.asarray(8.0),
            jnp.asarray(0.0), jnp.asarray(0.01), jnp.asarray(0.005),
        )
        rho, tau = np.asarray(rho), np.asarray(tau)
        assert (rho >= 0).all() and (tau >= 0).all()
        assert (rho + tau <= 1.0).all()

    def test_chlorophyll_darkens_red_not_nir(self):
        args = lambda cab: (
            jnp.asarray(1.5), jnp.asarray(cab), jnp.asarray(8.0),
            jnp.asarray(0.0), jnp.asarray(0.01), jnp.asarray(0.005),
        )
        rho_lo, _ = leaf_optics(*args(10.0))
        rho_hi, _ = leaf_optics(*args(70.0))
        # band 2 = B04 red: strong absorption difference
        assert float(rho_hi[2]) < float(rho_lo[2]) - 0.02
        # band 6 = B08 NIR: chlorophyll-transparent
        assert abs(float(rho_hi[6]) - float(rho_lo[6])) < 0.01

    def test_water_darkens_swir(self):
        args = lambda cw: (
            jnp.asarray(1.5), jnp.asarray(40.0), jnp.asarray(8.0),
            jnp.asarray(0.0), jnp.asarray(cw), jnp.asarray(0.005),
        )
        rho_dry, _ = leaf_optics(*args(0.002))
        rho_wet, _ = leaf_optics(*args(0.03))
        assert float(rho_wet[9]) < float(rho_dry[9]) - 0.02  # B12


class TestCanopyBRF:
    def test_bounds_and_finite(self):
        rng = np.random.default_rng(0)
        lo, hi = OP.state_bounds
        xs = jnp.asarray(
            rng.uniform(lo, hi, (256, 10)).astype(np.float32)
        )
        brf = np.asarray(OP.forward(AUX, xs))
        assert np.isfinite(brf).all()
        assert (brf >= 0).all() and (brf <= 1).all()

    def test_bare_soil_limit(self):
        """LAI -> 0: BRF must converge to the mixed soil spectrum."""
        x = make_state(lai=1e-4, bsoil=1.0, psoil=0.7)
        brf = np.asarray(OP.forward_pixel(AUX, x))
        soil = 1.0 * (0.7 * SOIL_DRY + 0.3 * SOIL_WET)
        np.testing.assert_allclose(brf, soil, atol=0.01)

    def test_dense_canopy_ignores_soil(self):
        """LAI -> large: soil brightness must stop mattering.  In the NIR
        (single-scatter albedo ~0.95) the diffuse field penetrates deep —
        e^{-mL} ~ 0.17 at LAI 8 — so a small residual soil effect is
        physical; only the visible bands extinguish it completely."""
        b1 = np.asarray(OP.forward_pixel(AUX, make_state(lai=8.0, bsoil=0.2)))
        b2 = np.asarray(OP.forward_pixel(AUX, make_state(lai=8.0, bsoil=1.8)))
        np.testing.assert_allclose(b1[:5], b2[:5], atol=0.005)  # VIS/red edge
        np.testing.assert_allclose(b1, b2, atol=0.03)           # incl. NIR

    def test_red_edge(self):
        """A vegetated canopy must be much brighter in NIR than red."""
        brf = np.asarray(OP.forward_pixel(AUX, make_state(lai=4.0, cab=50.0)))
        red, nir = brf[2], brf[6]
        assert nir > 2.0 * red

    def test_hotspot_brightening(self):
        """Backscatter geometry (view == sun) must be brighter than a
        well-separated geometry at the same angles."""
        x = make_state(lai=3.0)
        hot = ProsailAux(sza=jnp.asarray(30.0), vza=jnp.asarray(30.0),
                         raa=jnp.asarray(0.0))
        cold = ProsailAux(sza=jnp.asarray(30.0), vza=jnp.asarray(30.0),
                          raa=jnp.asarray(180.0))
        b_hot = np.asarray(OP.forward_pixel(hot, x))
        b_cold = np.asarray(OP.forward_pixel(cold, x))
        assert (b_hot >= b_cold - 1e-6).all()
        assert b_hot[6] > b_cold[6]  # visible in the NIR

    def test_jacobian_finite_and_informative(self):
        x = make_state()
        lin = OP.linearize(AUX, x[None, :])
        jac = np.asarray(lin.jac)
        assert np.isfinite(jac).all()
        # TLAI (slot 6) must influence the NIR band
        assert abs(jac[6, 0, 6]) > 1e-3

    def test_parameter_list_matches_state(self):
        assert len(PROSAIL_PARAMETER_LIST) == OP.n_params

    def test_inverse_transforms_roundtrip(self):
        x = make_state(lai=2.5, cab=33.0, cw=0.015, cm=0.007, ala=45.0)
        n, cab, car, cbrown, cw, cm, lai, ala, *_ = [
            float(v) for v in inverse_transforms(x)
        ]
        assert abs(lai - 2.5) < 1e-3
        assert abs(cab - 33.0) < 0.05
        assert abs(cw - 0.015) < 1e-5
        assert abs(ala - 45.0) < 0.05

    def test_leaf_structure_n_is_identity(self):
        # The reference S2 state carries leaf-structure N directly
        # (SAILPrior mean 2.1, kafka_test_S2.py:84); the transform must not
        # remap or saturate it inside the physical range.
        x = make_state(n=2.1)
        n = float(inverse_transforms(x)[0])
        assert abs(n - 2.1) < 1e-6

    def test_sail_prior_mean_strictly_inside_bounds(self):
        # A prior mean on (or beyond) a bound saturates the clip and zeroes
        # that parameter's Jacobian, silently making it unidentifiable.
        from kafka_tpu.engine.priors import sail_prior

        mean = np.asarray(sail_prior().prior.mean)
        lo, hi = OP.state_bounds
        assert (mean > lo).all(), (mean, lo)
        assert (mean < hi).all(), (mean, hi)


class TestAssimilation:
    def test_recover_lai_from_reflectance(self):
        """End-to-end sanity: generate reflectances from a known LAI and
        invert; the posterior TLAI must move toward the truth."""
        from kafka_tpu.core.solvers import iterated_solve
        from kafka_tpu.core.types import BandBatch
        from kafka_tpu.engine.priors import sail_prior

        truth = make_state(lai=3.0)
        prior = sail_prior()
        n_pix = 32
        y_true = np.asarray(OP.forward(AUX, jnp.tile(truth, (n_pix, 1))))
        rng = np.random.default_rng(1)
        y = y_true + rng.normal(0, 0.005, y_true.shape).astype(np.float32)
        r_inv = np.full_like(y, 1.0 / 0.005**2)
        bands = BandBatch(
            y=jnp.asarray(y), r_inv=jnp.asarray(r_inv),
            mask=jnp.ones_like(jnp.asarray(y), bool),
        )
        x0, p_inv0 = prior.process_prior(None, _FakeGather(n_pix))
        bounds = (jnp.asarray(OP.state_bounds[0]),
                  jnp.asarray(OP.state_bounds[1]))
        def linearize(aux, xx):
            return OP.linearize(AUX, xx)
        x, p_inv, diags = iterated_solve(
            linearize, bands, x0, p_inv0, None, state_bounds=bounds,
        )
        tlai_prior = float(np.asarray(x0)[0, 6])
        tlai_post = float(np.asarray(x)[:, 6].mean())
        tlai_true = float(truth[6])
        assert abs(tlai_post - tlai_true) < abs(tlai_prior - tlai_true)
        assert np.isfinite(np.asarray(x)).all()


class _FakeGather:
    def __init__(self, n_pad):
        self.n_pad = n_pad


class TestJacobianAgainstFiniteDifferences:
    def test_autodiff_matches_central_differences(self):
        """The solver trusts jacfwd through the full plate+SAIL chain;
        verify it against float32 central differences at the canonical
        state and at a stressed state (guards the spectral-constants
        swap and any future model edits)."""
        for state_kw in ({}, {"lai": 0.8, "cab": 12.0, "cw": 0.003}):
            x = np.asarray(make_state(**state_kw), np.float32)
            lin = OP.linearize(AUX, x[None, :])
            jac = np.asarray(lin.jac)[:, 0, :]          # (10, 10)
            eps = 1e-3
            for i in range(10):
                xp = x.copy()
                xm = x.copy()
                xp[i] += eps
                xm[i] -= eps
                fp = np.asarray(OP.forward_pixel(AUX, jnp.asarray(xp)))
                fm = np.asarray(OP.forward_pixel(AUX, jnp.asarray(xm)))
                fd = (fp - fm) / (2 * eps)
                np.testing.assert_allclose(
                    jac[:, i], fd, rtol=0.05, atol=5e-3,
                    err_msg=f"param {i} state {state_kw}",
                )
