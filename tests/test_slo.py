"""SLO engine (ISSUE 15): declarative objectives, multi-window
burn-rate alerts, the pending->firing->resolved state machine, the
alerts.jsonl ledger + error budgets, and the outward wiring (/alertz,
/healthz 503, admission slo_burn, live/fleet views, slo_report,
bench snapshot).

The chaos acceptance test pins the contract end to end: a
fault-injected rejection storm against a real AssimilationService
flips the availability objective pending -> firing within one fast
window, alerts.jsonl + /alertz + fleet_status agree on the firing
alert, admission sheds reason ``slo_burn`` when opted in, the alert
resolves after the storm heals with the consumed budget fraction on
the ledger — and the fault-free control run fires NOTHING (the
zero-false-alarm pin).
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from kafka_tpu import telemetry
from kafka_tpu.resilience import RetryPolicy, faults
from kafka_tpu.serve import AssimilationService
from kafka_tpu.serve.admission import (
    AdmissionController,
    AdmissionPolicy,
    RETRYABLE_REASONS,
)
from kafka_tpu.telemetry import MetricsRegistry, slo
from kafka_tpu.telemetry.httpd import TelemetryHTTPd

FAST2 = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)

#: seconds-fast windows for tier-1: two evaluations confirm a page
#: well inside one fast window.
TEST_WINDOWS = dict(fast_window_s=5.0, slow_window_s=20.0,
                    pending_for_s=0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class StubSession:
    """Duck-typed tile session (no JAX): the serve is a constant."""

    def __init__(self, name="t"):
        self.name = name
        self.serves = 0

    def serve(self, date):
        self.serves += 1
        return {"status": "ok", "x_sha256": "stub",
                "date": date.isoformat()}


def stub_service(tmp_path, policy=None):
    svc = AssimilationService(
        {"t": StubSession()}, str(tmp_path),
        policy=policy or AdmissionPolicy(max_queue_depth=8),
        retry_policy=FAST2,
    )
    return svc


def http_get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def http_get_allow_error(url):
    try:
        return http_get(url)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode()


# ---------------------------------------------------------------------------
# Objective signals over the registry vocabulary.
# ---------------------------------------------------------------------------

class TestSignals:
    def test_availability_counts_ok_vs_rejected_and_errors(self):
        reg = MetricsRegistry()
        obj = {o.name: o for o in slo.default_objectives()}
        good, bad = obj["availability"].signal(reg)
        assert (good, bad) == (0.0, 0.0)  # absent metrics read as zero
        reg.histogram("kafka_serve_latency_seconds", "t").observe(0.01)
        reg.counter("kafka_serve_rejected_total", "t").inc(
            3, reason="queue_full"
        )
        reg.counter("kafka_serve_rejected_total", "t").inc(
            2, reason="admit_error"
        )
        reg.counter("kafka_serve_errors_total", "t").inc(1)
        good, bad = obj["availability"].signal(reg)
        assert (good, bad) == (1.0, 6.0)  # reasons summed
        # The router's client-visible counters fold in too.
        reg.histogram("kafka_route_latency_seconds", "t").observe(0.02)
        reg.counter("kafka_route_rejected_total", "t").inc(
            1, reason="fleet_degraded"
        )
        good, bad = obj["availability"].signal(reg)
        assert (good, bad) == (2.0, 7.0)

    def test_latency_fraction_under_bar(self):
        reg = MetricsRegistry()
        h = reg.histogram("kafka_serve_latency_seconds", "t")
        for v in (0.01, 0.02, 0.1, 0.9):  # bar 250 ms: 3 under, 1 over
            h.observe(v)
        obj = [o for o in slo.default_objectives()
               if o.name == "latency"][0]
        good, bad = obj.signal(reg)
        assert (good, bad) == (3.0, 1.0)
        detail = obj.detail(reg)
        assert detail["bar_ms"] == slo.LATENCY_BAR_MS
        assert detail["p99_ms"] is not None

    def test_solver_signal_pixels_minus_quarantined(self):
        reg = MetricsRegistry()
        reg.counter("kafka_engine_pixels_total", "t").inc(1000)
        reg.counter(
            "kafka_solver_quarantined_pixels_total", "t"
        ).inc(7)
        obj = [o for o in slo.default_objectives()
               if o.name == "solver"][0]
        assert obj.signal(reg) == (993.0, 7.0)

    def test_gauge_signals_no_data_until_set(self):
        reg = MetricsRegistry()
        objs = {o.name: o for o in slo.default_objectives()}
        assert objs["quality"].signal(reg) is None
        assert objs["perf"].signal(reg) is None
        reg.gauge("kafka_quality_drift_active", "t").set(0)
        reg.gauge("kafka_perf_device_fraction", "t").set(0.8)
        assert objs["quality"].signal(reg) == 0.0
        assert objs["perf"].signal(reg) == 0.0
        reg.gauge("kafka_quality_drift_active", "t").set(2)
        reg.gauge("kafka_perf_device_fraction", "t").set(0.01)
        assert objs["quality"].signal(reg) == 1.0
        assert objs["perf"].signal(reg) == 1.0


# ---------------------------------------------------------------------------
# The alert state machine + burn-rate arithmetic (deterministic via
# evaluate_once(now=...) — no sleeps).
# ---------------------------------------------------------------------------

def storm(reg, n=10, reason="queue_full"):
    reg.counter(
        "kafka_serve_rejected_total",
        "requests shed at admission",
    ).inc(n, reason=reason)


class TestStateMachine:
    def make(self, reg, **kw):
        cfg = dict(TEST_WINDOWS)
        cfg.update(kw)
        return slo.SLOEngine(registry=reg, **cfg)

    def test_pending_then_firing_then_resolved(self):
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg)
            eng.evaluate_once(now=100.0)  # baseline
            storm(reg)
            s = eng.evaluate_once(now=100.5)
            avail = s["objectives"]["availability"]
            assert avail["status"] == "pending"
            assert avail["burn_fast"] > slo.FAST_BURN_THRESHOLD
            s = eng.evaluate_once(now=101.0)
            assert s["objectives"]["availability"]["status"] == "firing"
            assert {(a["objective"], a["severity"])
                    for a in s["firing"]} == {
                ("availability", "page"), ("availability", "warn"),
            }
            assert reg.value(
                "kafka_slo_alerts_firing", severity="page"
            ) == 1
            assert reg.value(
                "kafka_slo_alerts_fired_total", severity="page"
            ) == 1
            events = [e["event"] for e in reg.events]
            assert "slo_alert" in events
            # Storm heals: the fast window slides past the rejections,
            # the page resolves; the slow window still covers them.
            s = eng.evaluate_once(now=110.0)
            sev = s["objectives"]["availability"]["alerts"]
            assert sev["page"] == "ok" and sev["warn"] == "firing"
            assert reg.value(
                "kafka_slo_alerts_firing", severity="page"
            ) == 0
            assert "slo_resolved" in [e["event"] for e in reg.events]
            # ... and past the slow window everything resolves.
            s = eng.evaluate_once(now=140.0)
            assert s["objectives"]["availability"]["status"] == "ok"
            assert s["alerts_fired"] == 2
            assert s["alerts_resolved"] == 2

    def test_pending_clears_silently_without_confirmation(self):
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg, pending_for_s=10.0)
            eng.evaluate_once(now=100.0)
            storm(reg)
            s = eng.evaluate_once(now=100.5)
            assert s["objectives"]["availability"]["status"] == \
                "pending"
            # The PAGE breach ages out of the fast window before
            # pending_for_s elapses: that alert never fires (the slow
            # window legitimately still covers the storm, so only the
            # warn side may progress).
            s = eng.evaluate_once(now=120.0)
            assert s["objectives"]["availability"]["alerts"][
                "page"] == "ok"
            page_kinds = [r["kind"] for r in eng.ledger.records
                          if r["severity"] == "page"]
            assert "firing" not in page_kinds

    def test_clean_run_fires_nothing(self):
        """Zero-false-alarm pin: healthy traffic at any volume never
        alerts."""
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg)
            h = reg.histogram("kafka_serve_latency_seconds", "t")
            for i in range(50):
                h.observe(0.01)
                eng.evaluate_once(now=100.0 + i)
            s = eng.summary()
            assert s["alerts_fired"] == 0
            assert s["firing"] == []
            assert list(eng.ledger.records) == []
            assert all(
                o["status"] in ("ok", "no_data")
                for o in s["objectives"].values()
            )

    def test_gauge_objective_pages_on_sustained_drift(self):
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg)
            reg.gauge("kafka_quality_drift_active", "t").set(2)
            for i in range(3):
                s = eng.evaluate_once(now=100.0 + i)
            assert s["objectives"]["quality"]["status"] == "firing"
            assert ("quality", "page") in {
                (a["objective"], a["severity"]) for a in s["firing"]
            }

    def test_perf_objective_warns_but_cannot_page(self):
        """Target 0.90 bounds the burn at 10 < the 14.4 page
        threshold: a throughput floor breach warns on the slow window,
        never pages."""
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg)
            reg.gauge("kafka_perf_device_fraction", "t").set(0.001)
            s = None
            for i in range(40):
                s = eng.evaluate_once(now=100.0 + i)
            alerts = s["objectives"]["perf"]["alerts"]
            assert alerts["page"] == "ok"
            assert alerts["warn"] == "firing"

    def test_budget_ledger_consumed_and_tte(self):
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg, budget_window_s=3600.0)
            eng.evaluate_once(now=100.0)
            h = reg.histogram("kafka_serve_latency_seconds", "t")
            for _ in range(999):
                h.observe(0.01)
            storm(reg, n=1)
            s = eng.evaluate_once(now=101.0)
            b = s["objectives"]["availability"]["budget"]
            # 1 bad / 1000 total = exactly the 0.001 error budget.
            assert b["consumed"] == pytest.approx(1.0, rel=1e-3)
            assert b["remaining"] == pytest.approx(0.0, abs=1e-3)
        # Fresh engine, milder burn: budget partially consumed, tte
        # scales the budget window by the remaining fraction.
        with telemetry.use(MetricsRegistry()) as reg:
            eng = self.make(reg, budget_window_s=3600.0)
            eng.evaluate_once(now=100.0)
            h = reg.histogram("kafka_serve_latency_seconds", "t")
            for _ in range(1999):
                h.observe(0.01)
            storm(reg, n=1)
            s = eng.evaluate_once(now=101.0)
            b = s["objectives"]["availability"]["budget"]
            assert 0.4 < b["consumed"] < 0.6
            assert b["tte_s"] is not None and b["tte_s"] > 0

    def test_evaluator_thread_smoke(self):
        """The tracked background thread evaluates on its own and
        stop() lands a final round."""
        with telemetry.use(MetricsRegistry()) as reg:
            eng = slo.SLOEngine(registry=reg, interval_s=0.05,
                                **TEST_WINDOWS)
            eng.start()
            # Let the evaluator take its pre-traffic baseline sample
            # first — counters that climbed before the first
            # evaluation are history, not in-window burn.
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not \
                    reg.value("kafka_slo_evaluations_total"):
                time.sleep(0.02)
            storm(reg, n=20)
            try:
                while time.monotonic() < deadline:
                    if reg.value("kafka_slo_alerts_firing",
                                 severity="page"):
                        break
                    time.sleep(0.02)
                assert reg.value(
                    "kafka_slo_alerts_firing", severity="page"
                ) == 1
            finally:
                eng.stop()
            assert reg.value("kafka_slo_evaluations_total") >= 2
            names = [t.name for t in threading.enumerate()]
            assert "slo-evaluator" not in names


# ---------------------------------------------------------------------------
# alerts.jsonl: rotation discipline + loading.
# ---------------------------------------------------------------------------

class TestAlertLedger:
    def test_records_written_and_rotated(self, tmp_path):
        led = slo._AlertLedger(str(tmp_path), rotate_bytes=400, keep=2)
        for i in range(20):
            led.append({"schema": 1, "ts": float(i), "kind": "firing",
                        "objective": "availability",
                        "severity": "page"})
        names = sorted(os.listdir(tmp_path))
        assert slo.ALERTS_FILENAME in names
        assert any(n.startswith("alerts.jsonl.") for n in names)
        assert not any(n.endswith(".3") for n in names)  # keep=2
        records, skipped = slo.load_alerts(
            str(tmp_path / slo.ALERTS_FILENAME)
        )
        assert skipped == 0
        # Oldest-first across segments: timestamps monotone.
        ts = [r["ts"] for r in records]
        assert ts == sorted(ts)

    def test_torn_tail_skipped(self, tmp_path):
        path = tmp_path / slo.ALERTS_FILENAME
        rec = {"schema": 1, "ts": 1.0, "kind": "firing",
               "objective": "a", "severity": "page"}
        path.write_text(json.dumps(rec) + "\n" + '{"torn": ')
        records, skipped = slo.load_alerts(str(path))
        assert len(records) == 1 and skipped == 1

    def test_in_memory_without_directory(self):
        led = slo._AlertLedger(None)
        led.append({"kind": "firing", "objective": "a"})
        assert len(led.records) == 1 and led.path is None


# ---------------------------------------------------------------------------
# /alertz + /healthz + /statusz (satellite 1) and admission slo_burn.
# ---------------------------------------------------------------------------

class TestEndpoints:
    def test_alertz_and_healthz_flip_on_firing_page(self):
        with telemetry.use(MetricsRegistry()) as reg:
            eng = slo.get_engine(reg, **TEST_WINDOWS)
            httpd = TelemetryHTTPd(port=0, role="serve").start()
            try:
                code, body = http_get(httpd.url + "/alertz?json=1")
                assert code == 200
                assert json.loads(body)["enabled"] is True
                code, _ = http_get(httpd.url + "/healthz")
                assert code == 200
                eng.evaluate_once(now=100.0)
                storm(reg)
                eng.evaluate_once(now=100.5)
                eng.evaluate_once(now=101.0)
                # /alertz (json + text) reports the firing alert ...
                code, body = http_get(httpd.url + "/alertz?json=1")
                payload = json.loads(body)
                assert payload["objectives"]["availability"][
                    "status"] == "firing"
                code, text = http_get(httpd.url + "/alertz")
                assert "FIRING [page] availability" in text
                # ... /healthz flips 503 naming the objective
                # (satellite: load balancers inherit SLO awareness) ...
                code, body = http_get_allow_error(
                    httpd.url + "/healthz"
                )
                assert code == 503
                health = json.loads(body)
                assert health["verdict"] == "slo_burn"
                assert health["slo_firing"] == ["availability"]
                # ... and /statusz carries the summary inline.
                code, body = http_get(httpd.url + "/statusz")
                assert json.loads(body)["slo"]["objectives"][
                    "availability"]["status"] == "firing"
                # Resolution restores 200.
                eng.evaluate_once(now=140.0)
                code, body = http_get(httpd.url + "/healthz")
                assert code == 200
                assert json.loads(body)["slo_firing"] == []
            finally:
                httpd.close()

    def test_healthz_unprobed_stays_200_without_engine(self):
        with telemetry.use(MetricsRegistry()):
            httpd = TelemetryHTTPd(port=0).start()
            try:
                code, body = http_get(httpd.url + "/healthz")
                assert code == 200
                assert json.loads(body)["verdict"] == "unprobed"
                code, body = http_get(httpd.url + "/alertz")
                assert "not running" in body
            finally:
                httpd.close()


class TestAdmissionShedding:
    def test_sheds_slo_burn_when_opted_in(self):
        with telemetry.use(MetricsRegistry()) as reg:
            reg.gauge("kafka_slo_alerts_firing", "t").set(
                1, severity="page"
            )
            on = AdmissionController(AdmissionPolicy(shed_on_slo=True))
            off = AdmissionController(AdmissionPolicy())
            assert on.decide(queue_depth=0) == "slo_burn"
            assert off.decide(queue_depth=0) is None
            # slo_burn is a server-state rejection: it carries the
            # backoff hint.
            assert "slo_burn" in RETRYABLE_REASONS
            assert on.retry_after("slo_burn") == \
                AdmissionPolicy().retry_after_s

    def test_clears_when_alert_resolves(self):
        with telemetry.use(MetricsRegistry()) as reg:
            reg.gauge("kafka_slo_alerts_firing", "t").set(
                0, severity="page"
            )
            on = AdmissionController(AdmissionPolicy(shed_on_slo=True))
            assert on.decide(queue_depth=0) is None

    def test_router_policy_has_the_knob(self):
        from kafka_tpu.serve.router import (
            RETRYABLE_REJECTIONS, RoutePolicy,
        )

        assert RoutePolicy().shed_on_slo is False
        assert RoutePolicy(shed_on_slo=True).shed_on_slo is True
        assert "slo_burn" in RETRYABLE_REJECTIONS


# ---------------------------------------------------------------------------
# Live snapshots, fleet aggregation, fleet_status render.
# ---------------------------------------------------------------------------

class TestFleetView:
    def _snap(self, pid, firing):
        return {
            "schema": 1, "ts": time.time(), "host": "h", "pid": pid,
            "role": "serve", "seq": 1, "interval_s": 2.0,
            "final": False, "run_id": None, "chunk_id": None,
            "health": {"unhealthy": None},
            "counters": {}, "gauges": {}, "histograms": {},
            "slo": {
                "enabled": True, "started": True,
                "alerts_fired": len(firing), "alerts_resolved": 0,
                "firing": [
                    {"objective": o, "severity": s}
                    for o, s in firing
                ],
                "objectives": {},
            },
            "series_truncated": 0, "crash_dumps": [], "status": {},
        }

    def test_live_snapshot_carries_slo(self):
        from kafka_tpu.telemetry.live import build_snapshot

        with telemetry.use(MetricsRegistry()) as reg:
            eng = slo.get_engine(reg, **TEST_WINDOWS)
            eng.evaluate_once(now=100.0)
            snap = build_snapshot(reg, role="serve")
        assert snap["slo"]["enabled"] is True
        assert "availability" in snap["slo"]["objectives"]

    def test_fleet_dedupes_firing_objectives(self):
        from kafka_tpu.telemetry.aggregate import aggregate_fleet

        fleet = aggregate_fleet([
            self._snap(1, [("availability", "page")]),
            self._snap(2, [("availability", "page"),
                           ("latency", "warn")]),
            self._snap(3, []),
        ])
        firing = fleet["slo"]["firing"]
        assert {(f["objective"], f["severity"]) for f in firing} == {
            ("availability", "page"), ("latency", "warn"),
        }
        avail = [f for f in firing
                 if f["objective"] == "availability"][0]
        # One fleet alert, both workers attributed.
        assert avail["workers"] == ["h:1", "h:2"]
        assert fleet["slo"]["alerts_fired"] == 3

    def test_fleet_status_renders_alert_lines(self):
        from tools.fleet_status import render
        from kafka_tpu.telemetry.aggregate import aggregate_fleet

        fleet = aggregate_fleet([
            self._snap(1, [("availability", "page")]),
            self._snap(2, [("availability", "page")]),
        ])
        fleet["queue"] = None
        text = render(fleet)
        assert "slo=FIRING[availability(page)]" in text
        assert "SLO ALERT FIRING: availability [page] on h:1, h:2" \
            in text


# ---------------------------------------------------------------------------
# tools/slo_report.py: the error-budget report over alerts.jsonl.
# ---------------------------------------------------------------------------

class TestSloReport:
    def _run_episode(self, tmp_path):
        """One storm -> firing -> resolved arc with a ledger on disk;
        returns (engine summary, ledger dir)."""
        with telemetry.use(MetricsRegistry(str(tmp_path))) as reg:
            eng = slo.SLOEngine(registry=reg, **TEST_WINDOWS)
            eng.evaluate_once(now=100.0)
            reg.histogram(
                "kafka_serve_latency_seconds", "t"
            ).observe(0.01)
            storm(reg, n=10)
            eng.evaluate_once(now=100.5)
            eng.evaluate_once(now=101.0)
            eng.evaluate_once(now=140.0)
            return eng.summary(), str(tmp_path)

    def test_json_reproduces_episode_from_ledger_alone(
            self, tmp_path, capsys):
        from tools.slo_report import main

        summary, root = self._run_episode(tmp_path)
        rc = main([root, "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        # The episode reconstructs from alerts.jsonl ALONE: both
        # severities fired at 101.0 and resolved when their windows
        # slid clear.
        eps = {(e["objective"], e["severity"]): e
               for e in report["episodes"]}
        page = eps[("availability", "page")]
        assert page["pending_ts"] == 100.5
        assert page["firing_ts"] == 101.0
        assert page["resolved_ts"] == 140.0
        assert page["duration_s"] == pytest.approx(39.0)
        assert page["burn_fast"] > slo.FAST_BURN_THRESHOLD
        obj = report["objectives"]["availability"]
        assert obj["episodes"] == 2 and obj["open_episodes"] == 0
        assert obj["worst_burn_fast"] > slo.FAST_BURN_THRESHOLD
        # Budget remaining matches the live engine's final ledger.
        live_budget = summary["objectives"]["availability"]["budget"]
        assert obj["budget"]["remaining"] == pytest.approx(
            live_budget["remaining"], abs=1e-6
        )

    def test_human_render_and_open_episode(self, tmp_path, capsys):
        from tools.slo_report import main

        with telemetry.use(MetricsRegistry(str(tmp_path))) as reg:
            eng = slo.SLOEngine(registry=reg, **TEST_WINDOWS)
            eng.evaluate_once(now=100.0)
            storm(reg, n=10)
            eng.evaluate_once(now=100.5)
            eng.evaluate_once(now=101.0)  # firing, never resolved
        rc = main([str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "availability" in out and "OPEN" in out

    def test_no_ledger_is_usage_error(self, tmp_path, capsys):
        from tools.slo_report import main

        rc = main([str(tmp_path)])
        assert rc == 2

    def test_clean_ledger_reports_full_budget(self, tmp_path, capsys):
        from tools.slo_report import main

        (tmp_path / slo.ALERTS_FILENAME).write_text("")
        rc = main([str(tmp_path), "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["records"] == 0
        assert report["objectives"] == {}


# ---------------------------------------------------------------------------
# Chaos acceptance (ISSUE 15): rejection storm against a REAL service.
# ---------------------------------------------------------------------------

class TestChaosAcceptance:
    def test_rejection_storm_fires_resolves_and_sheds(self, tmp_path):
        """serve.admit fault storm -> availability pending -> firing
        within one fast window; alerts.jsonl + /alertz + fleet_status
        agree; admission sheds slo_burn (opted in); after the storm
        heals the alert resolves and the ledger carries the consumed
        budget fraction."""
        from tools.fleet_status import build_view
        from kafka_tpu.telemetry import live

        tel = str(tmp_path / "tel")
        with telemetry.use(MetricsRegistry(tel)) as reg:
            svc = stub_service(
                tmp_path / "serve",
                policy=AdmissionPolicy(max_queue_depth=64,
                                       shed_on_slo=True),
            ).start()
            eng = slo.get_engine(reg, fast_window_s=5.0,
                                 slow_window_s=12.0,
                                 pending_for_s=0.0)
            httpd = TelemetryHTTPd(port=0, role="serve").start()
            try:
                t0 = 1000.0
                eng.evaluate_once(now=t0)
                # Healthy traffic first: the control half of the run.
                for i in range(4):
                    ack = svc.submit(
                        {"tile": "t", "date": "2017-07-05",
                         "request_id": f"ok{i}"}
                    )
                    assert ack["status"] == "queued"
                    assert svc.result(f"ok{i}", timeout_s=30.0)[
                        "status"] == "ok"
                s = eng.evaluate_once(now=t0 + 0.2)
                assert s["firing"] == []
                # The storm: every admission faulted for 12 calls.
                faults.script("serve.admit", "1-12", faults.TRANSIENT)
                storm_start = t0 + 0.3
                for i in range(12):
                    ack = svc.submit(
                        {"tile": "t", "date": "2017-07-05",
                         "request_id": f"bad{i}"}
                    )
                    assert ack["status"] == "rejected"
                    assert ack["reason"] == "admit_error"
                # pending -> firing within ONE fast window.
                s = eng.evaluate_once(now=t0 + 0.5)
                assert s["objectives"]["availability"]["status"] == \
                    "pending"
                s = eng.evaluate_once(now=t0 + 0.8)
                assert s["objectives"]["availability"]["status"] == \
                    "firing"
                firing_rec = [r for r in eng.ledger.records
                              if r["kind"] == "firing"][0]
                assert firing_rec["ts"] - storm_start < \
                    eng.fast_window_s
                # alerts.jsonl, /alertz and fleet_status AGREE.
                records, skipped = slo.load_alerts(
                    os.path.join(tel, slo.ALERTS_FILENAME)
                )
                assert skipped == 0
                assert ("availability", "page", "firing") in {
                    (r["objective"], r["severity"], r["kind"])
                    for r in records
                }
                _, body = http_get(httpd.url + "/alertz?json=1")
                assert json.loads(body)["objectives"][
                    "availability"]["status"] == "firing"
                live.LivePublisher(tel, role="serve",
                                   registry=reg).publish_now()
                fleet = build_view(tel)
                assert {(f["objective"], f["severity"])
                        for f in fleet["slo"]["firing"]} >= {
                    ("availability", "page"),
                }
                # Admission sheds slo_burn while the page fires
                # (faults exhausted: the fault point passes now).
                ack = svc.submit({"tile": "t", "date": "2017-07-05",
                                  "request_id": "shed0"})
                assert ack["status"] == "rejected"
                assert ack["reason"] == "slo_burn"
                assert ack["retry_after_s"] > 0
                # Heal: one evaluation lands the shed rejection in a
                # sample (shedding IS burn — the operator's tradeoff),
                # then the windows slide past the whole storm, the
                # alert resolves and admission admits again.
                eng.evaluate_once(now=t0 + 5.0)
                s = eng.evaluate_once(now=t0 + 30.0)
                assert s["objectives"]["availability"]["status"] == \
                    "ok"
                assert s["alerts_resolved"] >= 2
                ack = svc.submit({"tile": "t", "date": "2017-07-05",
                                  "request_id": "after0"})
                assert ack["status"] == "queued"
                assert svc.result("after0", timeout_s=30.0)[
                    "status"] == "ok"
                # The budget ledger shows the storm's consumed
                # fraction (13 bad vs 6 ok >> the 0.001 budget).
                b = s["objectives"]["availability"]["budget"]
                assert b["consumed"] > 1.0
                assert b["remaining"] == 0.0
                resolved = [r for r in slo.load_alerts(
                    os.path.join(tel, slo.ALERTS_FILENAME)
                )[0] if r["kind"] == "resolved"]
                assert resolved and all(
                    r["budget"]["consumed"] > 1.0 for r in resolved
                )
            finally:
                httpd.close()
                svc.close()

    def test_fault_free_control_run_fires_nothing(self, tmp_path):
        """The zero-false-alarm pin: the identical setup without the
        fault storm alerts on NOTHING and writes no ledger."""
        tel = str(tmp_path / "tel")
        with telemetry.use(MetricsRegistry(tel)) as reg:
            svc = stub_service(tmp_path / "serve").start()
            eng = slo.get_engine(reg, fast_window_s=5.0,
                                 slow_window_s=12.0,
                                 pending_for_s=0.0)
            try:
                t0 = 1000.0
                eng.evaluate_once(now=t0)
                for i in range(16):
                    ack = svc.submit(
                        {"tile": "t", "date": "2017-07-05",
                         "request_id": f"c{i}"}
                    )
                    assert ack["status"] == "queued"
                    assert svc.result(f"c{i}", timeout_s=30.0)[
                        "status"] == "ok"
                    eng.evaluate_once(now=t0 + 0.1 * (i + 1))
                s = eng.evaluate_once(now=t0 + 30.0)
                assert s["alerts_fired"] == 0 and s["firing"] == []
                assert not os.path.exists(
                    os.path.join(tel, slo.ALERTS_FILENAME)
                )
            finally:
                svc.close()


# ---------------------------------------------------------------------------
# Engine-run integration: the pixels counter + driver wiring.
# ---------------------------------------------------------------------------

class TestEngineIntegration:
    def test_engine_counts_assimilated_pixels(self, tmp_path):
        """kafka_engine_pixels_total (the solver objective's
        denominator) counts n_valid per assimilated window with zero
        added device reads."""
        from test_quality import run_identity_engine

        kf, out, reg = run_identity_engine()
        pixels = reg.value("kafka_engine_pixels_total")
        windows = sum(
            v for (k, v) in [
                (key, val) for key, val in reg.flat().items()
                if key.startswith("kafka_engine_windows_total")
            ]
        )
        assert pixels is not None and pixels > 0
        assert pixels == kf.gather.n_valid * windows
        # The solver objective reads it: clean run -> zero bad.
        obj = [o for o in slo.default_objectives()
               if o.name == "solver"][0]
        good, bad = obj.signal(reg)
        assert good == pixels and bad == 0

    def test_run_synthetic_starts_and_stops_the_evaluator(
            self, tmp_path):
        """Driver wiring: a clean CPU run_synthetic run evaluates SLOs
        (evaluations counted, gauges exported) and fires nothing."""
        from kafka_tpu.cli.run_synthetic import main
        from kafka_tpu.telemetry import get_registry, set_registry

        tel = str(tmp_path / "tel")
        prev = get_registry()
        try:
            summary = main([
                "--operator", "identity", "--ny", "40", "--nx", "40",
                "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
            ])
        finally:
            set_registry(prev)
        assert summary["n_pixels"] > 0
        with open(os.path.join(tel, "metrics.prom")) as f:
            prom = f.read()
        assert "kafka_slo_evaluations_total" in prom
        assert 'kafka_slo_alerts_firing{severity="page"} 0' in prom
        # Clean run: no alert ledger (the zero-false-alarm pin at the
        # driver level), and the started event is on the record.
        assert not os.path.exists(
            os.path.join(tel, slo.ALERTS_FILENAME)
        )
        with open(os.path.join(tel, "events.jsonl")) as f:
            events = [json.loads(l)["event"] for l in f if l.strip()]
        assert "slo_engine_started" in events
