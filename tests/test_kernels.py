"""MOD09 / Ross-Li kernels observation path (VERDICT round-1 item 7).

Covers the kernel math against an independent scalar oracle, the QA bit
decoder against the reference's accepted-value whitelist
(``/root/reference/kafka/input_output/observations.py:101-102``), the
linear kernel-weights operator, the MOD09 granule reader, the Synergy
broadband integration, and an end-to-end kernel-weight retrieval.
"""

import datetime
import math

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_tpu.obsops.kernels import (
    KernelsAux,
    KernelsOperator,
    li_sparse_reciprocal,
    ross_li_kernels,
    ross_thick,
)
from kafka_tpu.io.mod09 import (
    MOD09Observations,
    decode_state_qa,
    zoom2_nearest,
)


def day(i):
    return datetime.datetime(2020, 6, 1) + datetime.timedelta(days=i)


# ---------------------------------------------------------------------------
# Kernel math
# ---------------------------------------------------------------------------


def oracle_ross_thick(sza, vza, raa):
    """Independent scalar RossThick (math module, no shared code)."""
    ts, tv, phi = map(math.radians, (sza, vza, raa))
    cx = math.cos(ts) * math.cos(tv) + \
        math.sin(ts) * math.sin(tv) * math.cos(phi)
    xi = math.acos(max(-1.0, min(1.0, cx)))
    return ((math.pi / 2 - xi) * math.cos(xi) + math.sin(xi)) / (
        math.cos(ts) + math.cos(tv)
    ) - math.pi / 4


def oracle_li_sparse_r(sza, vza, raa, hb=2.0, br=1.0):
    """Independent scalar LiSparse-Reciprocal."""
    ts = math.atan(br * math.tan(math.radians(sza)))
    tv = math.atan(br * math.tan(math.radians(vza)))
    phi = math.radians(raa)
    cx = math.cos(ts) * math.cos(tv) + \
        math.sin(ts) * math.sin(tv) * math.cos(phi)
    sec_sum = 1 / math.cos(ts) + 1 / math.cos(tv)
    d2 = math.tan(ts) ** 2 + math.tan(tv) ** 2 \
        - 2 * math.tan(ts) * math.tan(tv) * math.cos(phi)
    cost = hb * math.sqrt(
        max(d2, 0.0) + (math.tan(ts) * math.tan(tv) * math.sin(phi)) ** 2
    ) / sec_sum
    cost = max(-1.0, min(1.0, cost))
    t = math.acos(cost)
    overlap = (t - math.sin(t) * cost) * sec_sum / math.pi
    return overlap - sec_sum + 0.5 * (1 + cx) / (math.cos(ts) * math.cos(tv))


ANGLE_CASES = [
    (30.0, 10.0, 60.0),
    (55.0, 40.0, 120.0),
    (15.0, 45.0, -30.0),
    (5.0, 5.0, 180.0),
    (60.0, 0.0, 0.0),
]


class TestKernelMath:
    @pytest.mark.parametrize("sza,vza,raa", ANGLE_CASES)
    def test_matches_scalar_oracle(self, sza, vza, raa):
        kv, kg = ross_li_kernels(sza, vza, raa)
        assert float(kv) == pytest.approx(
            oracle_ross_thick(sza, vza, raa), abs=1e-6
        )
        assert float(kg) == pytest.approx(
            oracle_li_sparse_r(sza, vza, raa), abs=1e-6
        )

    def test_zero_at_nadir(self):
        """Both kernels are normalised to zero at (0, 0, 0) — the effect of
        the reference's ``normalise=1`` kernel construction."""
        kv, kg = ross_li_kernels(0.0, 0.0, 0.0)
        assert abs(float(kv)) < 1e-6
        assert abs(float(kg)) < 1e-6

    @pytest.mark.parametrize("sza,vza,raa", ANGLE_CASES)
    def test_reciprocity(self, sza, vza, raa):
        """Swapping illumination and view directions leaves both kernels
        unchanged (``RecipFlag=True`` semantics)."""
        a = ross_li_kernels(sza, vza, raa)
        b = ross_li_kernels(vza, sza, raa)
        np.testing.assert_allclose(a, b, atol=1e-6)

    @pytest.mark.parametrize("sza,vza,raa", ANGLE_CASES)
    def test_even_in_relative_azimuth(self, sza, vza, raa):
        a = ross_li_kernels(sza, vza, raa)
        b = ross_li_kernels(sza, vza, -raa)
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_vectorised_and_finite(self):
        rng = np.random.default_rng(0)
        sza = rng.uniform(0, 70, 1000).astype(np.float32)
        vza = rng.uniform(0, 65, 1000).astype(np.float32)
        raa = rng.uniform(-180, 180, 1000).astype(np.float32)
        kv = np.asarray(ross_thick(sza, vza, raa))
        kg = np.asarray(li_sparse_reciprocal(sza, vza, raa))
        assert kv.shape == kg.shape == (1000,)
        assert np.isfinite(kv).all() and np.isfinite(kg).all()


# ---------------------------------------------------------------------------
# QA decoder + regridding
# ---------------------------------------------------------------------------


class TestStateQA:
    def test_reference_whitelist_accepted(self):
        """Every QA word the reference whitelists decodes as clear land
        (``observations.py:101-102``)."""
        whitelist = np.array(
            [8, 72, 136, 200, 1032, 1288, 2056, 2120, 2184, 2248]
        )
        assert decode_state_qa(whitelist).all()

    def test_bad_conditions_rejected(self):
        bad = np.array([
            0b01,                # cloudy
            0b10,                # mixed clouds
            8 | 0b100,           # cloud shadow
            0,                   # water (land bits 000)
            8 | (0b10 << 8),     # average cirrus
            8 | (1 << 12),       # snow/ice
            8 | (1 << 13),       # adjacent to cloud
        ])
        assert not decode_state_qa(bad).any()

    def test_zoom2_nearest(self):
        a = np.array([[1, 2], [3, 4]])
        z = zoom2_nearest(a)
        assert z.shape == (4, 4)
        np.testing.assert_array_equal(
            z, np.array([[1, 1, 2, 2], [1, 1, 2, 2],
                         [3, 3, 4, 4], [3, 3, 4, 4]])
        )


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


class TestKernelsOperator:
    def test_forward_and_constant_jacobian(self):
        op = KernelsOperator(n_modis_bands=7)
        n_pix = 5
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.uniform(0, 0.5, (n_pix, 21)), jnp.float32)
        aux = KernelsAux(
            k_vol=jnp.asarray(rng.uniform(-0.1, 0.6, n_pix), jnp.float32),
            k_geo=jnp.asarray(rng.uniform(-1.2, 0.1, n_pix), jnp.float32),
        )
        lin = op.linearize(aux, x)
        assert lin.h0.shape == (7, n_pix)
        assert lin.jac.shape == (7, n_pix, 21)
        # h_b = iso + kvol*vol + kgeo*geo per band, per pixel
        w = np.asarray(x).reshape(n_pix, 7, 3)
        kv = np.asarray(aux.k_vol)[:, None]
        kg = np.asarray(aux.k_geo)[:, None]
        expect = (w[..., 0] + kv * w[..., 1] + kg * w[..., 2]).T
        np.testing.assert_allclose(np.asarray(lin.h0), expect, rtol=1e-5)
        # Jacobian rows touch only the band's own triplet: [1, kvol, kgeo]
        jac = np.asarray(lin.jac)
        for b in range(7):
            block = jac[b, :, 3 * b:3 * b + 3]
            np.testing.assert_allclose(block[:, 0], 1.0, atol=1e-6)
            np.testing.assert_allclose(
                block[:, 1], np.asarray(aux.k_vol), atol=1e-6
            )
            np.testing.assert_allclose(
                block[:, 2], np.asarray(aux.k_geo), atol=1e-6
            )
            off = np.delete(jac[b], np.s_[3 * b:3 * b + 3], axis=1)
            np.testing.assert_allclose(off, 0.0, atol=1e-6)

    def test_hessian_is_zero(self):
        """Linear operator => exact zero second derivatives (the Hessian
        correction becomes a no-op, as it must)."""
        op = KernelsOperator(n_modis_bands=2)
        aux = KernelsAux(
            k_vol=jnp.asarray([0.2, 0.3]), k_geo=jnp.asarray([-0.5, -0.4])
        )
        x = jnp.asarray(np.full((2, 6), 0.2), jnp.float32)
        hess = np.asarray(op.hessian(aux, x))
        np.testing.assert_allclose(hess, 0.0, atol=1e-7)


# ---------------------------------------------------------------------------
# Readers
# ---------------------------------------------------------------------------


class TestMOD09Reader:
    def test_granule_roundtrip(self, tmp_path):
        from kafka_tpu.engine.state import make_pixel_gather
        from kafka_tpu.testing.fixtures import make_mod09_granules

        ny, nx = 8, 8  # 1 km grid; 500 m state grid is 16x16
        dates = [day(0), day(4)]
        angles = [(30.0, 140.0, 10.0, 200.0), (42.0, 135.0, 25.0, 80.0)]
        truth = make_mod09_granules(
            str(tmp_path), dates, ny=ny, nx=nx, angles=angles
        )
        op = KernelsOperator(7)
        obs = MOD09Observations(str(tmp_path), op)
        assert obs.dates == dates

        mask = np.ones((2 * ny, 2 * nx), bool)
        gather = make_pixel_gather(mask, pad_multiple=256)
        dob = obs.get_observations(dates[1], gather)
        assert dob.bands.y.shape == (7, gather.n_pad)

        # Observed reflectance equals the kernel model at the truth weights
        sza, saa, vza, vaa = angles[1]
        kv, kg = ross_li_kernels(sza, vza, vaa - saa)
        w = truth.reshape(7, 3)
        expect = w[:, 0] + float(kv) * w[:, 1] + float(kg) * w[:, 2]
        got = np.asarray(dob.bands.y)[:, : gather.n_valid]
        np.testing.assert_allclose(
            got, expect[:, None] * np.ones_like(got), atol=2e-4
        )
        # int16 DN / 1e4 quantisation
        # aux kernels match the scene geometry
        np.testing.assert_allclose(
            np.asarray(dob.aux.k_vol)[: gather.n_valid], float(kv), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(dob.aux.k_geo)[: gather.n_valid], float(kg), atol=1e-5
        )
        # inverse-variance from the per-band fixed sigmas
        r = np.asarray(dob.bands.r_inv)[:, : gather.n_valid]
        np.testing.assert_allclose(
            r[0], 1.0 / 0.004**2, rtol=1e-5
        )
        # padding rows carry no information
        assert (np.asarray(dob.bands.r_inv)[:, gather.n_valid:] == 0).all()

    def test_cloudy_qa_masks_observations(self, tmp_path):
        from kafka_tpu.engine.state import make_pixel_gather
        from kafka_tpu.io.geotiff import read_geotiff, write_geotiff
        from kafka_tpu.testing.fixtures import make_mod09_granules

        make_mod09_granules(str(tmp_path), [day(0)], ny=4, nx=4)
        gran = next(tmp_path.glob("MOD09GA.A*"))
        qa_path = str(gran / "state_1km.tif")
        _, info = read_geotiff(qa_path)
        qa = np.full((4, 4), 8, np.uint16)
        qa[0, :] = 0b01  # cloudy row
        write_geotiff(qa_path, qa, info.geo)

        obs = MOD09Observations(str(tmp_path), KernelsOperator(7))
        gather = make_pixel_gather(np.ones((8, 8), bool), pad_multiple=64)
        dob = obs.get_observations(day(0), gather)
        m = np.asarray(dob.bands.mask)[0, : gather.n_valid].reshape(8, 8)
        assert not m[:2].any()   # cloudy 1 km row -> two 500 m rows masked
        assert m[2:].all()


class TestSynergyKernels:
    def test_broadband_integration(self, tmp_path):
        from kafka_tpu.engine.state import make_pixel_gather
        from kafka_tpu.io.modis import (
            BB_INTERCEPT,
            TO_NIR,
            TO_VIS,
            SynergyKernels,
            TO_BHR,
        )
        from kafka_tpu.testing.fixtures import make_synergy_series

        truth = make_synergy_series(
            str(tmp_path), [day(0), day(8)], ny=6, nx=6, kernel_unc=0.005
        )
        obs = SynergyKernels(str(tmp_path), operator=None)
        assert len(obs.dates) == 2
        gather = make_pixel_gather(np.ones((6, 6), bool), pad_multiple=64)
        dob = obs.get_observations(obs.dates[0], gather)

        v = gather.n_valid
        expect_vis = TO_VIS @ truth + BB_INTERCEPT[0]
        expect_nir = TO_NIR @ truth + BB_INTERCEPT[1]
        y = np.asarray(dob.bands.y)
        np.testing.assert_allclose(y[0, :v], expect_vis, rtol=1e-5)
        np.testing.assert_allclose(y[1, :v], expect_nir, rtol=1e-5)

        # variance propagated through both linear maps
        var_bhr = (TO_BHR**2).sum() * 0.005**2
        expect_var_vis = (TO_VIS**2).sum() * var_bhr
        r = np.asarray(dob.bands.r_inv)
        np.testing.assert_allclose(
            r[0, :v], 1.0 / expect_var_vis, rtol=1e-4
        )


# ---------------------------------------------------------------------------
# End-to-end retrieval
# ---------------------------------------------------------------------------


class TestKernelRetrieval:
    def test_filter_recovers_kernel_weights(self, tmp_path):
        """Assimilating several MOD09 dates with varying geometry must pull
        the kernel-weight state from a weak prior toward the truth — the
        MCD43-style inversion as a temporal filter."""
        from kafka_tpu.engine import KalmanFilter
        from kafka_tpu.engine.priors import kernels_prior
        from kafka_tpu.testing import MemoryOutput
        from kafka_tpu.testing.fixtures import make_mod09_granules

        ny, nx = 4, 4
        dates = [day(2 * i) for i in range(6)]
        truth = make_mod09_granules(
            str(tmp_path), dates, ny=ny, nx=nx, noise=0.002, seed=7
        )
        op = KernelsOperator(7)
        obs = MOD09Observations(str(tmp_path), op)
        prior = kernels_prior()
        out = MemoryOutput()
        mask = np.ones((2 * ny, 2 * nx), bool)
        kf = KalmanFilter(
            obs, out, mask, prior.parameter_list,
            state_propagation=None, prior=prior, pad_multiple=64,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.zeros(21, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        grid = [day(-1), day(3), day(7), day(11)]
        x_a, _, p_inv_a = kf.run(grid, x0, None, p_inv0)

        x_final = np.asarray(x_a)[: kf.gather.n_valid]
        err_iso = np.abs(
            x_final[:, 0::3] - truth.reshape(7, 3)[:, 0]
        ).mean()
        prior_err = np.abs(
            np.asarray(x0)[0, 0::3] - truth.reshape(7, 3)[:, 0]
        ).mean()
        assert err_iso < 0.02
        assert err_iso < prior_err / 3
