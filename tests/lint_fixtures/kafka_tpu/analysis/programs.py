"""Fixture registry twin: the AST-readable covered set for rule 21.

Parsed (never imported) by ``rules_programs.covered_entry_points`` when
kafkalint runs over the fixture tree — the names below are the fixture
defs that count as registered device programs, so only the deliberately
unregistered ones get flagged.
"""

COVERED_ENTRY_POINTS = {
    "leaky_update",
    "flagged_solve",
    "compliant",
    "sharded_double",
    "sharded_scale",
}
