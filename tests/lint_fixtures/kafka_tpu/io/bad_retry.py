"""Seeded ``ad-hoc-retry`` violations: hand-rolled backoff loops and
straight-line waits that must go through resilience.RetryPolicy."""

import time
from time import sleep


def flaky_read(read):
    for attempt in range(3):
        try:
            return read()
        except OSError:
            time.sleep(2 ** attempt)  # expect: ad-hoc-retry
    return None


def poll_until(done):
    while not done():
        sleep(0.5)  # expect: ad-hoc-retry


def wait_then_read(read):
    time.sleep(5.0)  # expect: ad-hoc-retry
    return read()
