"""Fixture: naive (non-atomic) marker writes — the queue-protocol
violations rule 11 must catch.  The .done/.failed/.lease markers are the
multi-host coordination protocol; a plain open(..., "w") can be read
half-written by a racing host."""

import json
import os


def mark_done_naively(outdir, prefix):
    with open(os.path.join(outdir, f".chunk_{prefix}.done"), "w") as f:  # expect: naive-marker-write
        json.dump({"finished": True}, f)


def grab_lease_naively(outdir, prefix, payload):
    open(outdir + f"/.chunk_{prefix}.lease", "w").write(  # expect: naive-marker-write
        json.dumps(payload)
    )


def _write_marker(path, payload):
    # The sanctioned helper itself may touch marker paths directly —
    # not flagged even though the literal names a marker suffix.
    with open(path + ".failed", "w") as f:
        json.dump(payload, f)


def read_is_fine(outdir, prefix):
    # Reads are not writes: no finding.
    with open(os.path.join(outdir, f".chunk_{prefix}.done")) as f:
        return json.load(f)
