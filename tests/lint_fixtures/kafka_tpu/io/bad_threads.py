"""Seeded untracked-thread violations plus the compliant PR 3 shape."""

import threading

from kafka_tpu.telemetry import tracing


def _bare_worker():
    while True:
        pass


def spawn_untracked():
    t = threading.Thread(target=_bare_worker, daemon=True)  # expect: untracked-thread
    u = threading.Thread(target=lambda: None)  # expect: untracked-thread
    return t, u


class Owner:
    """The convention: capture at construction, re-install in the target."""

    def __init__(self):
        self._ctx = tracing.current_context()
        self._worker = threading.Thread(target=self._drain, daemon=True)

    def _drain(self):
        tracing.set_context(self._ctx)
        tracing.set_lane("writer")
