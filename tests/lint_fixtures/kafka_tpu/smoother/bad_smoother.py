"""Seeded forward-state-mutation-in-smoother violations (rule 19): the
RTS backward pass is read-only over the checkpoint chain — writing a
checkpoint set or a chain node's forward fields from the smoother
package breaks the any-replica-can-serve-it contract."""

import numpy as np


def rewind_chain(checkpointer, timestep, x_s, p_s_inv):
    checkpointer.save(timestep, x_s, p_s_inv)  # expect: forward-state-mutation-in-smoother


def patch_node_in_place(node, x_s, p_f_inv):
    node.x_analysis = x_s  # expect: forward-state-mutation-in-smoother
    node.sidecar = (x_s, p_f_inv)  # expect: forward-state-mutation-in-smoother
    return node


def overwrite_shard(path, x, p_inv):
    with open(path, "wb") as f:
        np.savez_compressed(f, x_analysis=x, p_inv_tril=p_inv)  # expect: forward-state-mutation-in-smoother
