"""Fixture: magic-quality-threshold violations (ISSUE 11) — quality
threshold literals defined outside the sanctioned config block of
kafka_tpu/telemetry/quality.py."""

CHI2_CONSISTENT_HI = 2.75  # expect: magic-quality-threshold


def is_drifting(ratio):
    drift_threshold = 4.0  # expect: magic-quality-threshold
    return ratio > drift_threshold


def make_sentinel(sentinel_cls):
    # A locally-tuned CUSUM decision threshold diverges from the fleet's.
    return sentinel_cls(cusum_h=9.0)  # expect: magic-quality-threshold


def suppressed_threshold():
    # kafkalint: disable=magic-quality-threshold — fixture-local pin, never shipped
    ewma_band = 0.75
    return ewma_band
