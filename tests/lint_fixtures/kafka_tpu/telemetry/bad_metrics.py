"""Seeded telemetry-vocabulary violations (metric/event/phase rules)."""


def setup(reg, span):
    reg.counter("badName")  # expect: metric-name
    reg.counter("kafka_engine_dup_total")  # expect: metric-name
    reg.counter("kafka_engine_dup_total")
    reg.emit("chunkDone", n=1)  # expect: event-name, event-collision
    reg.emit("chunk_done", n=1)
    with span("dump"):  # expect: event-collision
        reg.emit("dump", n=1)
    reg.gauge("kafka_engine_fine_depth")
    reg.emit("run_done", n=2)
