"""Seeded violations: blocking outbound calls inside the telemetry
publisher/httpd tree (rule 13, ``blocking-call-in-publisher``).  The
heartbeat/endpoint threads run in every process — an unbounded HTTP
fetch, raw socket connect or subprocess there stalls the heartbeat and
reads as a dead host."""

import socket
import subprocess
from urllib.request import urlopen

import requests


def scrape_peer(url):
    return requests.get(url)  # expect: blocking-call-in-publisher


def dial(host):
    return socket.create_connection((host, 80))  # expect: blocking-call-in-publisher


def raw_socket():
    return socket.socket()  # expect: blocking-call-in-publisher


def shell_out():
    return subprocess.check_output(["hostname"])  # expect: blocking-call-in-publisher


def fetch(url):
    return urlopen(url)  # expect: blocking-call-in-publisher


def identity_is_fine():
    # Local and non-blocking: the snapshot's identity field.
    return socket.gethostname()
