"""Fixture: magic-slo-threshold violations (ISSUE 15) — SLO literals
defined outside the sanctioned config block of
kafka_tpu/telemetry/slo.py."""

FAST_BURN = 10.0  # expect: magic-slo-threshold


def over_budget(rate):
    budget = 0.001  # expect: magic-slo-threshold
    return rate > budget


def make_engine(engine_cls):
    # A locally-tuned burn threshold diverges from the fleet's page rule.
    return engine_cls(slow_burn=3.0)  # expect: magic-slo-threshold


def fine_names():
    # Vocabulary matches SEGMENTS, not substrings: these are not SLO
    # names even though 'slo' appears inside them.
    slowest = 4.2
    slopes = 1.5
    return slowest + slopes


def suppressed_threshold():
    # kafkalint: disable=magic-slo-threshold — fixture-local pin, never shipped
    slo_target = 0.95
    return slo_target
