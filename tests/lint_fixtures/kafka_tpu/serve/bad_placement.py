"""Seeded nondeterministic-placement violations (rule 16): salted /
random routing decisions in a placement-bearing tree."""

import random


def pick_owner(tile, replicas):
    idx = hash(tile) % len(replicas)  # expect: nondeterministic-placement
    return replicas[idx]


def spread(tile, replicas):
    return random.choice(replicas)  # expect: nondeterministic-placement


def jittered_shard(chunks, rng):
    import numpy as np

    order = np.random.permutation(len(chunks))  # expect: nondeterministic-placement
    return [chunks[i] for i in order]
