"""Seeded violations: serving-daemon worker threads and response polls
are subject to the same runtime conventions as everyone else —
untracked-thread (PR 3 tracing) and ad-hoc-retry (PR 6 resilience)."""

import threading
import time


def _serve_worker():
    # No tracing.set_context — this worker's spans detach from the run.
    return None


def spawn_worker():
    t = threading.Thread(target=_serve_worker, daemon=True)  # expect: untracked-thread
    t.start()
    return t


def wait_for_response(path_exists):
    while not path_exists():
        time.sleep(0.05)  # expect: ad-hoc-retry
