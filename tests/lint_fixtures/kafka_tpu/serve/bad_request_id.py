"""Seeded request-id-origin violations (rule 17): minting or
literal-constructing request ids outside the sanctioned origin
(serve/request.py) forks the per-request trace."""

import os
import secrets
import uuid


def mint_with_uuid():
    return uuid.uuid4().hex  # expect: request-id-origin


def mint_with_urandom():
    return os.urandom(8).hex()  # expect: request-id-origin


def mint_with_token_hex():
    return secrets.token_hex(8)  # expect: request-id-origin


def rebuild_id(base, attempt, submit):
    payload = {"request_id": f"{base}-{attempt}"}  # expect: request-id-origin
    payload["request_id"] = base + "-retry"  # expect: request-id-origin
    submit(request_id="manual-001")  # expect: request-id-origin
    return payload
