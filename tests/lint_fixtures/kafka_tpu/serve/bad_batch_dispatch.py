"""Seeded unbatched-serve-dispatch violations (rule 22): launching a
solve around the batch executor (serve/batch.py) — the request never
meets its batch peers and the coalescing telemetry under-counts."""

from kafka_tpu.core.solvers import assimilate_date_jit  # expect: unbatched-serve-dispatch


def serve_directly(session, date):
    return session.serve(date)  # expect: unbatched-serve-dispatch


def serve_smoothed_directly(session, date):
    return session.serve(date, smoothed=True)  # expect: unbatched-serve-dispatch


def dispatch_raw(linearize, obs, x, p_inv, aux, opts, hess):
    return assimilate_date_jit(  # expect: unbatched-serve-dispatch
        linearize, obs, x, p_inv, aux, opts, hess,
    )
