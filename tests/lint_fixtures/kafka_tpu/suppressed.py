"""Violations under inline suppressions — kafkalint must report NOTHING.

Exercises the trailing form, the comment-block-above form, and the
precedence of ``kafkalint: disable`` over the bare-except comment check.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def hush(x):
    a = np.asarray(x)  # kafkalint: disable=host-transfer-in-jit — parity probe
    # The read below is deliberate: this fixture documents the
    # comment-block form of the directive.
    # kafkalint: disable=host-transfer-in-jit
    b = float(x[0])
    return jnp.asarray(a) + b


def quiet(fn):
    try:
        fn()
    except Exception:  # kafkalint: disable=bare-except
        pass
