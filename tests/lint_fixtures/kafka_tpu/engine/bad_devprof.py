"""Seeded violations: raw device introspection outside the telemetry
funnel (rule 20, ``raw-device-introspection``).  ``memory_stats()``,
``jax.live_arrays()`` and ``jax.profiler.*`` belong in
``kafka_tpu/telemetry/{device,devprof,perf}.py`` — scattered call
sites duplicate the watermark gauges, race the buffer census, and
collide with the one-capture-per-process profiler contract."""

import jax
from jax import live_arrays, profiler


def adhoc_watermark(device):
    return device.memory_stats()  # expect: raw-device-introspection


def adhoc_census():
    return jax.live_arrays()  # expect: raw-device-introspection


def adhoc_census_bare():
    return live_arrays()  # expect: raw-device-introspection


def adhoc_capture(logdir):
    jax.profiler.start_trace(logdir)  # expect: raw-device-introspection
    profiler.stop_trace()  # expect: raw-device-introspection


def reading_the_gauges_is_fine(reg):
    # The sanctioned path: consume what the funnel published.
    return reg.value("kafka_device_memory_headroom_bytes")
