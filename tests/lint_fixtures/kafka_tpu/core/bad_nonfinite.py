"""Seeded nonfinite-launder violations (outside the solver_health
sanctuary): silently replacing NaN/inf with plausible numbers instead
of raising a solve-health verdict."""

import jax.numpy as jnp


def launder(x, fallback):
    a = jnp.nan_to_num(x)  # expect: nonfinite-launder
    b = jnp.where(jnp.isnan(x), fallback, x)  # expect: nonfinite-launder
    c = jnp.where(~jnp.isfinite(x), 0.0, x)  # expect: nonfinite-launder
    ok_select = jnp.where(x > 0, fallback, x)
    ok_probe = jnp.isfinite(x)  # detection alone raises no verdict lie
    return a, b, c, ok_select, ok_probe
