"""Seeded host-transfer violation inside a shard_map body.

Parsed by tests/test_lint.py, never imported.  Exercises jitscan's
shard_map recognition: both the call form (``shard_map(f, ...)``) and
the decorator form (``@partial(shard_map, ...)``) make the wrapped def a
jit region, so the host transfer seeded in ``sharded_double`` is caught
exactly like one inside ``@jax.jit``.  Both defs are listed in the
fixture ``COVERED_ENTRY_POINTS`` so rule 21 stays quiet here.
"""

import functools

import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map

MESH = object()  # stand-in; the file is parsed, never run


def sharded_double(block):
    leaked = np.asarray(block)  # expect: host-transfer-in-jit
    return jnp.asarray(leaked) * 2.0


double = shard_map(sharded_double, mesh=MESH, in_specs=None,
                   out_specs=None)


@functools.partial(shard_map, mesh=MESH, in_specs=None, out_specs=None)
def sharded_scale(block):
    # decorator form: a per-shard device program, but a clean one.
    return block * 0.5
