"""Fixture: ad-hoc timing in a device-adjacent module (rule 15).

Raw clock reads and block_until_ready timing barriers in core/ must go
through telemetry.spans (span / stopwatch); each seeded violation is
annotated with the rule expected to report it.
"""

import time
from time import monotonic

import jax


def timed_solve(solve, x):
    t0 = time.perf_counter()  # expect: ad-hoc-timing
    y = solve(x)
    jax.block_until_ready(y)  # expect: ad-hoc-timing
    return y, time.perf_counter() - t0  # expect: ad-hoc-timing


def poll_wall():
    start = monotonic()  # expect: ad-hoc-timing
    return start


def sanctioned_wall_clock():
    # time.time() is wall-clock bookkeeping (timestamps, deadlines),
    # not an interval measurement — stays legal.
    return time.time()


def suppressed_probe(solve, x):
    # kafkalint: disable=ad-hoc-timing — justified one-off calibration
    t0 = time.perf_counter()
    solve(x)
    return time.perf_counter() - t0  # kafkalint: disable=ad-hoc-timing
