"""Seeded unregistered-device-program violation.

Parsed by tests/test_lint.py, never imported.  ``rogue_solve`` is a
jitted entry point in a device package whose def name is NOT in the
fixture ``COVERED_ENTRY_POINTS`` — a device program no contract
analyzes, which is exactly what rule 21 exists to flag.
"""

import jax


@jax.jit
def rogue_solve(x, p_inv):  # expect: unregistered-device-program
    return x * 2.0 + p_inv.sum(-1)
