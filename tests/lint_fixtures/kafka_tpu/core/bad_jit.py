"""Seeded host-transfer-in-jit and static-arg-flag violations.

Parsed by tests/test_lint.py, never imported.  This path sits under
``kafka_tpu/core/`` so kafkalint classifies it as a device-code module.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def leaky_update(x, p_inv):
    y = np.asarray(x)  # expect: host-transfer-in-jit
    s = float(x[0])  # expect: host-transfer-in-jit
    t = x.sum().item()  # expect: host-transfer-in-jit
    d = jax.device_get(p_inv)  # expect: host-transfer-in-jit
    return jnp.asarray(y) + s + t + d


@functools.partial(jax.jit, static_argnums=(2,))
def flagged_solve(x, use_pallas: bool, block: int = 128, mode: str = "gn"):  # expect: static-arg-flag, static-arg-flag
    return x


def scan_with_host_io(xs):
    def body(carry, inp):
        np.save("/tmp/leak.npy", inp)  # expect: host-transfer-in-jit
        return carry, inp

    return jax.lax.scan(body, 0.0, xs)


@functools.partial(jax.jit, static_argnums=(1, 2))
def compliant(x, interpret: bool = False, mode: str = "gn"):
    # Statics named in static_argnums, float() on a static shape read,
    # and host numpy only OUTSIDE the jit region: all fine.
    return x * 2.0


def host_side(x):
    n = float(x.shape[0])
    return np.asarray(x) + n
