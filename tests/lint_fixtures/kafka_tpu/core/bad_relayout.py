"""kernel-relayout seeds: dense (B, n, p) Jacobian relayouts in core/
outside the sanctioned ``jac_to_rows`` compat shim."""

import jax.numpy as jnp


def leak_jacobian_rows(lin, n_bands, p, n):
    jac_rows = jnp.moveaxis(lin.jac, 2, 1).reshape(n_bands * p, n)  # expect: kernel-relayout
    swapped = jnp.transpose(lin.jac, (1, 0, 2))  # expect: kernel-relayout
    return jac_rows, swapped


def leak_method_form(jac, n_bands, p, n):
    flat = jac.reshape(n_bands * p, n)  # expect: kernel-relayout
    rolled = jac.swapaxes(0, 1)  # expect: kernel-relayout
    return flat, rolled


def jac_to_rows(jac):
    """A local shim definition is sanctioned — its body never flags."""
    return jnp.moveaxis(jac, 2, 1).reshape(-1, jac.shape[1])


def relayout_of_other_arrays_is_fine(x, state):
    # Non-Jacobian relayouts are the kernel's normal layout work.
    cols = jnp.transpose(x)
    stacked = state.reshape(-1, 4)
    return cols, stacked
