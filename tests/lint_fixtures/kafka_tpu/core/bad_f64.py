"""Seeded implicit-f64 violations (device-code module by path)."""

import jax.numpy as jnp
import numpy as np


def promote(x):
    a = np.asarray(x, np.float64)  # expect: implicit-f64
    b = jnp.zeros(4, dtype="float64")  # expect: implicit-f64
    c = jnp.asarray(0.5)  # expect: implicit-f64
    d = jnp.array([1.0, -2.5])  # expect: implicit-f64
    ok_dtype = jnp.asarray(0.5, jnp.float32)
    ok_var = jnp.asarray(x)
    ok_int = jnp.asarray(3)
    return a, b, c, d, ok_dtype, ok_var, ok_int
