"""Seeded bare-except violations plus every accepted escape hatch."""

import logging

LOG = logging.getLogger(__name__)


def swallow_exception(fn):
    try:
        fn()
    except Exception:  # expect: bare-except
        pass


def swallow_everything(fn):
    result = None
    try:
        result = fn()
    except:  # expect: bare-except
        result = -1
    return result


def swallow_base(fn):
    try:
        fn()
    except BaseException:  # expect: bare-except
        pass


def justified(fn):
    try:
        fn()
    except Exception:  # best-effort cache warm; the cold path is correct
        pass


def logged(fn):
    try:
        fn()
    except Exception as exc:
        LOG.warning("fn failed: %s", exc)


def reraised(fn):
    try:
        fn()
    except Exception:
        raise


def narrowed(fn):
    try:
        fn()
    except (OSError, ValueError):
        pass
