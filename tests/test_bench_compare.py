"""tools/bench_compare.py (ISSUE 3 satellite): the BENCH-trajectory gate —
>10% regression on any ``device_*_ms`` row exits non-zero, unhealthy
artifacts are never judged, telemetry snapshots are diffed for context."""

import importlib.util
import json
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "bench_compare",
        os.path.join(REPO_ROOT, "tools", "bench_compare.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def artifact(xla_ms=6.4, pallas_ms=3.8, unhealthy=False, telemetry=None):
    return {
        "metric": "assimilation_throughput",
        "device_xla_ms": xla_ms,
        "device_xla_ms_spread": 0.1,
        "device_pallas_ms": pallas_ms,
        "device_pallas_ms_spread": 0.1,
        "device_ms_matched_median": 1.2,
        "unhealthy": unhealthy,
        "telemetry": telemetry or {
            "kafka_engine_device_reads_total": 8,
            "kafka_compile_cache_hits_total": 3,
        },
    }


def write(tmp_path, name, art):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        json.dump(art, f)
    return p


class TestCompareRows:
    def test_no_regression_within_threshold(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            artifact(), artifact(xla_ms=6.4 * 1.05)
        )
        assert regressions == []

    def test_regression_beyond_threshold_flagged(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            artifact(), artifact(xla_ms=6.4 * 1.2)
        )
        assert len(regressions) == 1
        assert "device_xla_ms" in regressions[0]

    def test_spread_rows_never_gated(self):
        bc = _load()
        new = artifact()
        new["device_xla_ms_spread"] = 99.0  # noisy spread, same median
        regressions, _ = bc.compare_rows(artifact(), new)
        assert regressions == []

    def test_null_pallas_rows_skipped(self):
        """Off-TPU artifacts carry null Pallas rows; they must not gate
        (or crash) a comparison against a TPU artifact."""
        bc = _load()
        off_tpu = artifact(pallas_ms=None)
        regressions, lines = bc.compare_rows(off_tpu, artifact())
        assert regressions == []
        assert any("device_pallas_ms" in ln and "skipped" in ln
                   for ln in lines)

    def test_disappeared_row_is_a_gating_failure(self):
        """A device_*_ms row the old artifact carried that is missing
        (or null) in the new one is a dropped measurement — the kernel
        path silently stopped being measured — and gates like a
        regression instead of passing as 'not shared'."""
        bc = _load()
        gone = artifact()
        del gone["device_pallas_ms"]
        regressions, lines = bc.compare_rows(artifact(), gone)
        assert len(regressions) == 1
        assert "device_pallas_ms" in regressions[0]
        assert "disappeared" in regressions[0]
        # Nulled (off-TPU re-measure) gates identically to deleted.
        regressions_null, _ = bc.compare_rows(
            artifact(), artifact(pallas_ms=None)
        )
        assert len(regressions_null) == 1

    def test_appearing_row_still_skipped(self):
        """Coverage GROWING (a new row in the new artifact, e.g.
        device_pallas_fused_lin_ms landing) must never fail the gate."""
        bc = _load()
        grown = artifact()
        grown["device_pallas_fused_lin_ms"] = 2.1
        regressions, lines = bc.compare_rows(artifact(), grown)
        assert regressions == []
        assert any("device_pallas_fused_lin_ms" in ln and "skipped" in ln
                   for ln in lines)

    def test_improvement_not_flagged(self):
        bc = _load()
        regressions, lines = bc.compare_rows(
            artifact(xla_ms=6.4), artifact(xla_ms=3.9)
        )
        assert regressions == []
        assert any("improved" in ln for ln in lines)


class TestMain:
    def test_exit_zero_on_parity(self, tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact())
        assert bc.main([old, new]) == 0

    def test_exit_nonzero_on_regression(self, tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact(xla_ms=7.5))
        assert bc.main([old, new]) == 1

    def test_unhealthy_artifact_never_judged(self, tmp_path, capsys):
        """A regression measured against (or by) an off-band window is
        weather, not code — the verdict downgrades to unjudgeable."""
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(
            tmp_path, "new.json", artifact(xla_ms=9.0, unhealthy=True)
        )
        assert bc.main([old, new]) == 0
        assert "UNJUDGEABLE" in capsys.readouterr().err

    def test_custom_threshold(self, tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact(xla_ms=6.4 * 1.07))
        assert bc.main([old, new]) == 0
        assert bc.main([old, new, "--threshold", "0.05"]) == 1

    def test_exit_nonzero_on_disappeared_row(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        gone = artifact()
        del gone["device_pallas_ms"]
        new = write(tmp_path, "new.json", gone)
        assert bc.main([old, new]) == 1
        assert "disappeared" in capsys.readouterr().err

    def test_disappeared_row_unjudgeable_when_unhealthy(self, tmp_path,
                                                        capsys):
        """The unhealthy downgrade applies to disappearance too: an
        off-band window that skipped a measurement is weather."""
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        gone = artifact(unhealthy=True)
        del gone["device_pallas_ms"]
        new = write(tmp_path, "new.json", gone)
        assert bc.main([old, new]) == 0
        assert "UNJUDGEABLE" in capsys.readouterr().err

    def test_missing_file_is_usage_error(self, tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        assert bc.main([old, str(tmp_path / "nope.json")]) == 2

    def test_telemetry_deltas_reported(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact(telemetry={
            "kafka_engine_device_reads_total": 16,
            "kafka_compile_cache_hits_total": 3,
        }))
        assert bc.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "kafka_engine_device_reads_total: 8 -> 16" in out


def serve_artifact(p50=5.0, p99=20.0, rejected=0, unhealthy=False,
                   **kw):
    art = artifact(unhealthy=unhealthy, **kw)
    art.update({
        "serve_p50_ms": p50, "serve_p99_ms": p99,
        "serve_cold_ms": 800.0, "serve_rejected_total": rejected,
        "serve_requests_total": 24,
    })
    return art


class TestServeRowGating:
    """The serving-latency rows gate like the device rows (ISSUE 8
    satellite): >10% regression or disappearance of serve_p50_ms /
    serve_p99_ms fails; cold-start and rejection counts stay
    informational."""

    def test_serve_rows_are_gated(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            serve_artifact(), serve_artifact(p99=20.0 * 1.5)
        )
        assert len(regressions) == 1
        assert "serve_p99_ms" in regressions[0]

    def test_serve_regression_within_threshold_ok(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            serve_artifact(), serve_artifact(p50=5.0 * 1.05)
        )
        assert regressions == []

    def test_disappeared_serve_row_gates(self, tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", serve_artifact())
        gone = serve_artifact()
        gone["serve_p50_ms"] = None  # the failed-serve-bench null
        new = write(tmp_path, "new.json", gone)
        assert bc.main([old, new]) == 1

    def test_rejected_and_cold_rows_not_gated(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            serve_artifact(rejected=0),
            serve_artifact(rejected=1000) | {"serve_cold_ms": 99999.0},
        )
        assert regressions == []

    def test_old_artifact_without_serve_rows_unaffected(self, tmp_path):
        """Pre-serving artifacts (BENCH_r0*.json) gain rows in the new
        artifact: informational, never a gate failure."""
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", serve_artifact())
        assert bc.main([old, new]) == 0

    def test_serve_regression_unjudgeable_when_unhealthy(self, tmp_path,
                                                         capsys):
        bc = _load()
        old = write(tmp_path, "old.json", serve_artifact())
        new = write(tmp_path, "new.json",
                    serve_artifact(p50=50.0, unhealthy=True))
        assert bc.main([old, new]) == 0
        assert "UNJUDGEABLE" in capsys.readouterr().err


def smoother_artifact(ms=12.5, px_s=1.0e7, smoothed_p99=35.0, **kw):
    art = artifact(**kw)
    art.update({
        "device_smoother_ms": ms,
        "device_smoother_px_s": px_s,
        "serve_smoothed_p99_ms": smoothed_p99,
    })
    return art


class TestSmootherRowGating:
    """The reanalysis rows gate: device_smoother_ms via the device_*_ms
    pattern, serve_smoothed_p99_ms like the forward serving rows, and
    device_smoother_px_s with the regression direction INVERTED
    (throughput — larger is better)."""

    def test_smoother_ms_gates_via_device_pattern(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            smoother_artifact(), smoother_artifact(ms=12.5 * 1.5)
        )
        assert len(regressions) == 1
        assert "device_smoother_ms" in regressions[0]

    def test_smoothed_p99_gates(self):
        bc = _load()
        regressions, _ = bc.compare_rows(
            smoother_artifact(), smoother_artifact(smoothed_p99=60.0)
        )
        assert len(regressions) == 1
        assert "serve_smoothed_p99_ms" in regressions[0]

    def test_px_s_drop_is_a_regression(self):
        """Throughput FALLING by more than the threshold gates — the
        direction device_*_ms gating would read as an improvement."""
        bc = _load()
        regressions, _ = bc.compare_rows(
            smoother_artifact(px_s=1.0e7),
            smoother_artifact(px_s=0.8e7),
        )
        assert len(regressions) == 1
        assert "device_smoother_px_s" in regressions[0]

    def test_px_s_rise_is_an_improvement(self):
        bc = _load()
        regressions, lines = bc.compare_rows(
            smoother_artifact(px_s=1.0e7),
            smoother_artifact(px_s=1.5e7),
        )
        assert regressions == []
        assert any("device_smoother_px_s" in ln and "improved" in ln
                   for ln in lines)

    def test_disappeared_px_s_row_gates(self, tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", smoother_artifact())
        gone = smoother_artifact()
        gone["device_smoother_px_s"] = None  # failed-smoother-bench null
        new = write(tmp_path, "new.json", gone)
        assert bc.main([old, new]) == 1

    def test_old_artifact_without_smoother_rows_unaffected(self,
                                                           tmp_path):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", smoother_artifact())
        assert bc.main([old, new]) == 0


def health_artifact(quarantined=0, cap=0, **kw):
    art = artifact(**kw)
    art["solver_health"] = {
        "quarantined_pixels": quarantined,
        "cap_bailouts": cap,
        "damped_recoveries": 0,
        "nonfinite": 0,
        "clip_saturated": 0,
    }
    return art


class TestSolverHealthDeltas:
    """ISSUE 9 satellite: solver-health snapshot rows diff
    informationally (like telemetry), and a NEW nonzero
    quarantined_pixels on a previously-clean benchmark warns — never
    gates, never silence."""

    def test_deltas_reported_not_gated(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json",
                    health_artifact(quarantined=0, cap=2))
        new = write(tmp_path, "new.json",
                    health_artifact(quarantined=0, cap=7))
        assert bc.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "solver-health deltas" in out
        assert "cap_bailouts: 2 -> 7" in out

    def test_new_nonzero_quarantined_warns(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", health_artifact(quarantined=0))
        new = write(tmp_path, "new.json", health_artifact(quarantined=5))
        assert bc.main([old, new]) == 0  # a warning, not a gate
        err = capsys.readouterr().err
        assert "WARNING" in err and "quarantined_pixels went 0 -> 5" in err

    def test_preexisting_quarantine_does_not_warn(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", health_artifact(quarantined=4))
        new = write(tmp_path, "new.json", health_artifact(quarantined=5))
        assert bc.main([old, new]) == 0
        assert "WARNING" not in capsys.readouterr().err

    def test_artifacts_without_snapshot_unaffected(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact())
        assert bc.main([old, new]) == 0
        out = capsys.readouterr()
        assert "solver-health deltas" not in out.out
        assert "WARNING" not in out.err


def slo_artifact(fired=0, firing=(), budget_remaining=1.0, **kw):
    art = artifact(**kw)
    art["slo"] = {
        "enabled": True, "alerts_fired": fired,
        "alerts_resolved": fired, "firing": list(firing),
        "objectives": {},
    }
    art["serve_slo_alerts_total"] = fired
    art["serve_slo_budget_remaining"] = budget_remaining
    return art


class TestSloDeltas:
    """ISSUE 15 satellite: the "slo" snapshot + serve_slo_* rows diff
    informationally, and fired alerts on a previously-clean benchmark
    warn LOUDLY — never gate, never silence."""

    def test_deltas_reported_not_gated(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json",
                    slo_artifact(fired=1, budget_remaining=0.9))
        new = write(tmp_path, "new.json",
                    slo_artifact(fired=2, budget_remaining=0.5))
        assert bc.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "slo deltas" in out
        assert "alerts_fired: 1 -> 2" in out
        assert "serve_slo_budget_remaining: 0.9 -> 0.5" in out

    def test_fired_alerts_on_clean_benchmark_warn(self, tmp_path,
                                                  capsys):
        bc = _load()
        old = write(tmp_path, "old.json", slo_artifact(fired=0))
        new = write(tmp_path, "new.json",
                    slo_artifact(fired=3,
                                 firing=["availability:page"]))
        assert bc.main([old, new]) == 0  # a warning, not a gate
        err = capsys.readouterr().err
        assert "WARNING" in err and "SLO alerts fired went 0 -> 6" \
            in err

    def test_preexisting_alerts_do_not_warn(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", slo_artifact(fired=2))
        new = write(tmp_path, "new.json", slo_artifact(fired=3))
        assert bc.main([old, new]) == 0
        assert "WARNING" not in capsys.readouterr().err

    def test_artifacts_without_snapshot_unaffected(self, tmp_path,
                                                   capsys):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact())
        assert bc.main([old, new]) == 0
        out = capsys.readouterr()
        assert "slo deltas" not in out.out
        assert "WARNING" not in out.err


def contracts_artifact(programs=None, findings=0, error=None, **kw):
    art = artifact(**kw)
    art["program_contracts"] = {
        "programs": dict(
            programs if programs is not None
            else {"date_twostream_inkernel": "a" * 16,
                  "linearize_twostream": "b" * 16}
        ),
        "findings": findings,
        "clean": findings == 0,
        "error": error,
    }
    return art


class TestProgramContractDeltas:
    """ISSUE 19 satellite: the "program_contracts" snapshot diffs
    informationally, and a fingerprint drifting on a shared program —
    the two artifacts measured DIFFERENT device programs under the same
    name — warns LOUDLY; never gates, never silence."""

    def test_deltas_reported_not_gated(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", contracts_artifact())
        new = write(tmp_path, "new.json", contracts_artifact(
            programs={"date_twostream_inkernel": "a" * 16,
                      "linearize_twostream": "b" * 16,
                      "linearize_wcm": "c" * 16}))
        assert bc.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "program-contract deltas" in out
        assert "programs: 2 -> 3 (0 fingerprint(s) drifted)" in out
        assert "new program: linearize_wcm" in out

    def test_fingerprint_drift_warns_loudly(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", contracts_artifact())
        new = write(tmp_path, "new.json", contracts_artifact(
            programs={"date_twostream_inkernel": "f" * 16,
                      "linearize_twostream": "b" * 16}))
        assert bc.main([old, new]) == 0  # a warning, not a gate
        captured = capsys.readouterr()
        assert "date_twostream_inkernel: fingerprint" in captured.out
        assert "WARNING" in captured.err
        assert "drifted for date_twostream_inkernel" in captured.err
        assert "--update" in captured.err

    def test_new_contract_findings_warn(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", contracts_artifact(findings=0))
        new = write(tmp_path, "new.json", contracts_artifact(findings=3))
        assert bc.main([old, new]) == 0
        captured = capsys.readouterr()
        assert "contract findings: 0 -> 3" in captured.out
        assert "WARNING" in captured.err
        assert "contract findings went 0 -> 3" in captured.err

    def test_stable_fingerprints_do_not_warn(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", contracts_artifact())
        new = write(tmp_path, "new.json", contracts_artifact())
        assert bc.main([old, new]) == 0
        assert "WARNING" not in capsys.readouterr().err

    def test_artifacts_without_snapshot_unaffected(self, tmp_path,
                                                   capsys):
        bc = _load()
        old = write(tmp_path, "old.json", artifact())
        new = write(tmp_path, "new.json", artifact())
        assert bc.main([old, new]) == 0
        out = capsys.readouterr()
        assert "program-contract deltas" not in out.out
        assert "WARNING" not in out.err

    def test_analysis_error_is_reported(self, tmp_path, capsys):
        bc = _load()
        old = write(tmp_path, "old.json", contracts_artifact())
        new = write(tmp_path, "new.json", contracts_artifact(
            programs={}, findings=None,
            error="RuntimeError: trace failed"))
        assert bc.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "new: analysis error: RuntimeError: trace failed" in out
