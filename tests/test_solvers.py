"""Parity of the batched TPU solver against the sparse SciPy oracle.

The oracle mirrors the reference formulas (``solvers.py:100-145``,
``linear_kf.py:245-307``); these tests are the numerical spec the reference's
own (broken) tests never provided — SURVEY.md §4.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_tpu.core import (
    BandBatch,
    Linearization,
    build_normal_equations,
    iterated_solve,
    kalman_update,
    linear_solve,
)
from kafka_tpu.testing import oracle

RNG = np.random.default_rng(42)


def random_problem(n_pix=37, p=7, n_bands=2, mask_frac=0.2):
    """A random nonlinear-free linearised problem with masked observations."""
    jac = RNG.normal(size=(n_bands, n_pix, p)).astype(np.float32)
    h0 = RNG.normal(size=(n_bands, n_pix)).astype(np.float32)
    y = RNG.normal(size=(n_bands, n_pix)).astype(np.float32)
    r_inv = RNG.uniform(0.5, 2.0, size=(n_bands, n_pix)).astype(np.float32)
    mask = RNG.uniform(size=(n_bands, n_pix)) > mask_frac
    x_forecast = RNG.normal(size=(n_pix, p)).astype(np.float32)
    x_lin = x_forecast + 0.1 * RNG.normal(size=(n_pix, p)).astype(np.float32)
    # SPD prior information blocks
    w = RNG.normal(size=(n_pix, p, p)).astype(np.float32)
    p_inv = np.einsum("npq,nrq->npr", w, w) + 3.0 * np.eye(p, dtype=np.float32)
    return jac, h0, y, r_inv, mask, x_forecast, x_lin, p_inv


def to_band_batch(y, r_inv, mask):
    return BandBatch(
        y=jnp.asarray(np.where(mask, y, 0.0)),
        r_inv=jnp.asarray(np.where(mask, r_inv, 0.0)),
        mask=jnp.asarray(mask),
    )


class TestKalmanUpdate:
    def test_matches_sparse_oracle(self):
        jac, h0, y, r_inv, mask, x_f, x_lin, p_inv = random_problem()
        obs = to_band_batch(y, r_inv, mask)
        lin = Linearization(h0=jnp.asarray(h0), jac=jnp.asarray(jac))
        x_tpu, a_tpu = kalman_update(
            lin, obs, jnp.asarray(x_lin), jnp.asarray(x_f), jnp.asarray(p_inv)
        )
        x_ref, a_ref = oracle.sparse_multiband_solve(
            list(h0), list(jac), list(y), list(r_inv), list(mask),
            x_lin, x_f, p_inv,
        )
        np.testing.assert_allclose(
            np.asarray(x_tpu).ravel(), x_ref, rtol=2e-4, atol=2e-4
        )
        # The Hessian A must equal the oracle's sparse A blockwise.
        n_pix, p = x_f.shape
        a_dense = np.asarray(a_ref.todense())
        for i in range(0, n_pix, 7):
            sl = slice(i * p, (i + 1) * p)
            np.testing.assert_allclose(
                np.asarray(a_tpu)[i], a_dense[sl, sl], rtol=1e-4, atol=1e-4
            )

    def test_masked_observation_equals_dropped_row(self):
        """r_inv = 0 must give the identical posterior to physically removing
        the observation (the mathematically-correct version of the
        reference's y=0 hack, solvers.py:53)."""
        jac, h0, y, r_inv, mask, x_f, x_lin, p_inv = random_problem(
            n_pix=5, n_bands=3, mask_frac=0.0
        )
        mask = np.ones_like(mask)
        mask[1, 2] = False  # drop band 1 of pixel 2
        obs = to_band_batch(y, r_inv, mask)
        lin = Linearization(h0=jnp.asarray(h0), jac=jnp.asarray(jac))
        x_a, _ = kalman_update(
            lin, obs, jnp.asarray(x_lin), jnp.asarray(x_f), jnp.asarray(p_inv)
        )
        # Oracle with the row genuinely removed (r_inv -> 0 there).
        r0 = r_inv.copy()
        r0[1, 2] = 0.0
        x_ref, _ = oracle.sparse_multiband_solve(
            list(h0), list(jac), list(y), list(r0),
            list(np.ones_like(mask)), x_lin, x_f, p_inv,
        )
        np.testing.assert_allclose(
            np.asarray(x_a).ravel(), x_ref, rtol=2e-4, atol=2e-4
        )

    def test_single_band(self):
        jac, h0, y, r_inv, mask, x_f, x_lin, p_inv = random_problem(n_bands=1)
        obs = to_band_batch(y, r_inv, mask)
        lin = Linearization(h0=jnp.asarray(h0), jac=jnp.asarray(jac))
        x_tpu, _ = kalman_update(
            lin, obs, jnp.asarray(x_lin), jnp.asarray(x_f), jnp.asarray(p_inv)
        )
        x_ref, _ = oracle.sparse_multiband_solve(
            list(h0), list(jac), list(y), list(r_inv), list(mask),
            x_lin, x_f, p_inv,
        )
        np.testing.assert_allclose(
            np.asarray(x_tpu).ravel(), x_ref, rtol=2e-4, atol=2e-4
        )


class TestIteratedSolve:
    def test_nonlinear_convergence_matches_oracle(self):
        """Full Gauss-Newton loop on a genuinely nonlinear obs operator
        (quadratic model) must converge to the oracle's solution with the
        same loop-control semantics."""
        n_pix, p, n_bands = 23, 4, 2
        coeff = RNG.uniform(0.5, 1.5, size=(n_bands, p)).astype(np.float32)
        x_f = np.full((n_pix, p), 0.8, np.float32)
        x_true = x_f + RNG.normal(0.0, 0.05, size=(n_pix, p)).astype(np.float32)
        y = np.stack(
            [np.einsum("p,np->n", c, x_true**2) for c in coeff]
        ).astype(np.float32)
        r_inv = np.full((n_bands, n_pix), 25.0, np.float32)
        mask = np.ones((n_bands, n_pix), bool)
        p_inv = np.broadcast_to(
            4.0 * np.eye(p, dtype=np.float32), (n_pix, p, p)
        ).copy()

        def forward_np(x):  # (n_pix, p) -> per-band h0, jac lists
            h0 = [np.einsum("p,np->n", c, x**2) for c in coeff]
            jac = [2.0 * c[None, :] * x for c in coeff]
            return h0, jac

        def linearize_jax(x):
            h0 = jnp.stack(
                [jnp.einsum("p,np->n", jnp.asarray(c), x**2) for c in coeff]
            )
            jac = jnp.stack([2.0 * jnp.asarray(c)[None, :] * x for c in coeff])
            return Linearization(h0=h0, jac=jac)

        obs = to_band_batch(y, r_inv, mask)
        x_tpu, a_tpu, diags = iterated_solve(
            linearize_jax, obs, jnp.asarray(x_f), jnp.asarray(p_inv)
        )
        x_ref, a_ref, n_iter_ref = oracle.iterated_sparse_solve(
            forward_np, list(y), list(r_inv), list(mask), x_f, p_inv
        )
        np.testing.assert_allclose(
            np.asarray(x_tpu).ravel(), x_ref, rtol=5e-4, atol=5e-4
        )
        assert int(diags.n_iterations) == n_iter_ref
        assert float(diags.convergence_norm) < 1e-3

    def test_loop_bails_at_cap(self):
        """A pathological operator that never converges must stop after the
        reference's hard cap (26 solves: n_iter > 25, linear_kf.py:299)."""
        n_pix, p = 4, 3
        obs = to_band_batch(
            np.ones((1, n_pix), np.float32),
            np.ones((1, n_pix), np.float32),
            np.ones((1, n_pix), bool),
        )

        def linearize(x):
            # Oscillating linearisation -> no convergence.
            h0 = 100.0 * jnp.sin(37.0 * x.sum(-1))[None, :]
            jac = jnp.ones((1, n_pix, p)) * jnp.cos(37.0 * x.sum(-1))[None, :, None] * 50.0
            return Linearization(h0=h0, jac=jac)

        x_f = jnp.zeros((n_pix, p), jnp.float32)
        p_inv = jnp.broadcast_to(jnp.eye(p), (n_pix, p, p)).astype(jnp.float32)
        _, _, diags = iterated_solve(linearize, obs, x_f, p_inv)
        assert int(diags.n_iterations) == 26

    def test_linear_operator_converges_in_min_iterations(self):
        """With a linear operator the second iterate equals the first, so the
        loop must exit at exactly min_iterations = 2 solves."""
        jac, h0, y, r_inv, mask, x_f, _x_lin, p_inv = random_problem()

        def linearize(x):
            return Linearization(
                h0=jnp.einsum("bnp,np->bn", jnp.asarray(jac), x),
                jac=jnp.asarray(jac),
            )

        obs = to_band_batch(y, r_inv, mask)
        _, _, diags = iterated_solve(
            linearize, obs, jnp.asarray(x_f), jnp.asarray(p_inv)
        )
        assert int(diags.n_iterations) == 2


class TestLinearSolve:
    def test_identity_operator_scalar_update(self):
        """Identity H, diagonal prior: posterior must equal the closed-form
        scalar Bayes update per pixel/param."""
        n_pix, p = 11, 3
        x_f = RNG.normal(size=(n_pix, p)).astype(np.float32)
        y = RNG.normal(size=(1, n_pix)).astype(np.float32)
        r_inv = np.full((1, n_pix), 4.0, np.float32)
        prior_info = 2.0
        p_inv = np.broadcast_to(
            prior_info * np.eye(p, dtype=np.float32), (n_pix, p, p)
        ).copy()
        # H observes parameter 0 only.
        jac = np.zeros((1, n_pix, p), np.float32)
        jac[0, :, 0] = 1.0
        h0 = x_f[:, 0][None, :]
        obs = BandBatch(
            y=jnp.asarray(y), r_inv=jnp.asarray(r_inv),
            mask=jnp.ones((1, n_pix), bool),
        )
        lin = Linearization(h0=jnp.asarray(h0), jac=jnp.asarray(jac))
        x_a, a, diags = linear_solve(
            lin, obs, jnp.asarray(x_f), jnp.asarray(p_inv)
        )
        expected0 = (4.0 * y[0] + prior_info * x_f[:, 0]) / (4.0 + prior_info)
        np.testing.assert_allclose(
            np.asarray(x_a)[:, 0], expected0, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(x_a)[:, 1:], x_f[:, 1:], rtol=1e-5
        )


class TestHessianCorrection:
    """Oracle parity of the second-order correction
    (``kf_tools.py:26-72``: corr = sum_b ddH * r_inv * innovation, masked;
    ``linear_kf.py:416``: A_corrected = A - corr)."""

    N_BANDS, N_PIX, P = 3, 11, 4

    def _quad_forward(self, params, x_pixel):
        # y_b = c_b + 0.5 x^T Q_b x: constant per-band Hessian Q_b.
        q, c = params
        return c + 0.5 * jnp.einsum("bpq,p,q->b", q, x_pixel, x_pixel)

    def _problem(self):
        rng = np.random.default_rng(7)
        w = rng.normal(size=(self.N_BANDS, self.P, self.P))
        q = (w + np.swapaxes(w, -1, -2)).astype(np.float32)  # symmetric
        c = rng.normal(size=(self.N_BANDS,)).astype(np.float32)
        y = rng.normal(0.0, 1.0, (self.N_BANDS, self.N_PIX)).astype(
            np.float32)
        r_inv = rng.uniform(0.5, 2.0, y.shape).astype(np.float32)
        mask = rng.uniform(size=y.shape) > 0.25
        x_f = rng.normal(0.0, 0.3, (self.N_PIX, self.P)).astype(np.float32)
        p_inv = np.tile(
            5.0 * np.eye(self.P, dtype=np.float32), (self.N_PIX, 1, 1)
        )
        return (q, c), to_band_batch(y, r_inv, mask), x_f, p_inv

    def _linearize(self, params, x):
        q, c = params
        h0 = c[:, None] + 0.5 * jnp.einsum(
            "bpq,np,nq->bn", q, x, x
        )
        jac = jnp.einsum("bpq,nq->bnp", q, x)
        return Linearization(h0=h0, jac=jac)

    def test_matches_numpy_oracle(self):
        params, obs, x_f, p_inv = self._problem()
        common = (self._linearize, obs, jnp.asarray(x_f), jnp.asarray(p_inv),
                  params)
        x_plain, a_plain, diags = iterated_solve(*common)
        x_corr, a_corr, _ = iterated_solve(
            *common, hessian_forward=self._quad_forward
        )
        # The correction must not change the state, only the information.
        np.testing.assert_allclose(np.asarray(x_corr), np.asarray(x_plain))

        # NumPy oracle of the reference loop: per pixel, per band,
        # ddH * r_inv * innovation with masked pixels contributing zero
        # (kf_tools.py:49-52).  The innovations are the solver's own
        # returned ones (y - H0 at the last linearisation point) — the
        # reference passes them straight from the solver into
        # hessian_correction (linear_kf.py:412-416), while ddH is evaluated
        # at x_analysis.
        q, c = params
        innov = np.asarray(diags.innovations)
        r_inv = np.asarray(obs.r_inv)
        mask = np.asarray(obs.mask)
        corr = np.zeros((self.N_PIX, self.P, self.P), np.float32)
        for b in range(self.N_BANDS):
            for i in range(self.N_PIX):
                if not mask[b, i]:
                    continue
                corr[i] += np.asarray(q)[b] * r_inv[b, i] * innov[b, i]
        np.testing.assert_allclose(
            np.asarray(a_corr), np.asarray(a_plain) - corr, rtol=2e-4,
            atol=2e-4,
        )

    def test_masked_pixels_uncorrected(self):
        params, obs, x_f, p_inv = self._problem()
        all_masked = BandBatch(
            y=obs.y, r_inv=obs.r_inv,
            mask=jnp.zeros_like(obs.mask),
        )
        _, a_plain, _ = iterated_solve(
            self._linearize, all_masked, jnp.asarray(x_f),
            jnp.asarray(p_inv), params,
        )
        _, a_corr, _ = iterated_solve(
            self._linearize, all_masked, jnp.asarray(x_f),
            jnp.asarray(p_inv), params, hessian_forward=self._quad_forward,
        )
        np.testing.assert_allclose(np.asarray(a_corr), np.asarray(a_plain))


class TestBlockedLinearize:
    """linearize_block must be numerically identical to the unblocked path
    (it exists purely to bound peak device memory)."""

    def test_blocked_equals_unblocked(self):
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import iterated_solve
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(700)  # not block-aligned
        args = dict(
            obs=bands, x_forecast=x0, p_inv_forecast=p_inv0,
            operator_params=None,
            state_bounds=(
                jnp.asarray(op.state_bounds[0]),
                jnp.asarray(op.state_bounds[1]),
            ),
        )
        x_ref, a_ref, d_ref = iterated_solve(op.linearize, **args)
        x_blk, a_blk, d_blk = iterated_solve(
            op.linearize, linearize_block=256, **args
        )
        # Blocked evaluation reorders float32 fusions, and the GN loop
        # feeds those last-ulp differences back on itself — agreement is
        # to solver tolerance, not bitwise.
        np.testing.assert_allclose(
            np.asarray(x_blk), np.asarray(x_ref), atol=5e-3
        )
        np.testing.assert_allclose(
            np.asarray(a_blk), np.asarray(a_ref), rtol=2e-2, atol=1e-2
        )
        assert int(d_blk.n_iterations) == int(d_ref.n_iterations)

    def test_blocked_with_per_pixel_aux(self):
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import _blocked_linearize, _call_linearize
        from kafka_tpu.obsops.wcm import WCMAux, WCMOperator

        n = 130  # forces edge-padding with block=64
        rng = np.random.default_rng(0)
        op = WCMOperator()
        x = jnp.asarray(
            np.stack([rng.uniform(0.5, 5, n), rng.uniform(0.05, 0.5, n)],
                     axis=1), jnp.float32
        )
        aux = WCMAux(theta_deg=jnp.asarray(
            rng.uniform(20, 45, n).astype(np.float32)
        ))
        ref = _call_linearize(op.linearize, aux, x)
        blk = _blocked_linearize(op.linearize, aux, x, 64)
        np.testing.assert_allclose(
            np.asarray(blk.h0), np.asarray(ref.h0), atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(blk.jac), np.asarray(ref.jac), atol=1e-6
        )


class TestPallasSolve:
    """The Pallas packed-Cholesky kernel must match the XLA-fused path."""

    def _packed_problem(self, n=512, p=7, seed=0):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        m = rng.normal(size=(n, p, p)).astype(np.float32)
        a = m @ m.transpose(0, 2, 1) + 5 * np.eye(p, dtype=np.float32)
        b = rng.normal(size=(n, p)).astype(np.float32)
        from kafka_tpu.core.linalg import pack_symmetric

        return pack_symmetric(jnp.asarray(a)), jnp.asarray(b), a, b

    def test_matches_xla_packed_path(self):
        from kafka_tpu.core.linalg import solve_spd_packed
        from kafka_tpu.core.pallas_solve import solve_spd_packed_pallas

        for p in (2, 7, 10):
            a_packed, b, a_np, b_np = self._packed_problem(p=p, seed=p)
            x_ref = np.asarray(solve_spd_packed(a_packed, b))
            x_pl = np.asarray(
                solve_spd_packed_pallas(a_packed, b, interpret=True)
            )
            np.testing.assert_allclose(x_pl, x_ref, rtol=2e-5, atol=2e-5)
            # and against a float64 numpy solve
            x64 = np.linalg.solve(
                a_np.astype(np.float64),
                b_np.astype(np.float64)[..., None],
            )[..., 0]
            np.testing.assert_allclose(x_pl, x64, rtol=2e-3, atol=2e-3)

    def test_iterated_solve_use_pallas_option(self):
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(512)
        opts = {"state_bounds": (
            jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
        )}
        x_ref, a_ref, d_ref = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, None, opts
        )
        x_pl, a_pl, d_pl = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, None,
            {**opts, "use_pallas": True},
        )
        # Tolerance 2e-3, NOT float-exact: the fused kernel accumulates
        # the rank-1 band sums in a different order than XLA's ~40-fusion
        # schedule, and the Gauss-Newton relinearisation feeds those
        # last-ulp float32 differences back on itself across iterations.
        # Measured drift on the real chip at 2^19 px is max |dx| = 1.28e-3
        # (round-5 verification, queued-slope session); 2e-3 covers it
        # with margin while still catching semantic bugs, which show up
        # orders of magnitude larger (wrong mask handling ~1e-1+).
        np.testing.assert_allclose(
            np.asarray(x_pl), np.asarray(x_ref), atol=2e-3
        )
        assert int(d_pl.n_iterations) == int(d_ref.n_iterations)

    def test_non_divisible_pixel_counts(self):
        """Engine batches are multiples of 128/256, not of the 1024 max
        block — every such count must solve (block falls back to the gcd)."""
        from kafka_tpu.core.linalg import solve_spd_packed
        from kafka_tpu.core.pallas_solve import solve_spd_packed_pallas

        for n in (1280, 256, 384):
            a_packed, b, _, _ = self._packed_problem(n=n, p=7, seed=n)
            x_ref = np.asarray(solve_spd_packed(a_packed, b))
            x_pl = np.asarray(
                solve_spd_packed_pallas(a_packed, b, interpret=True)
            )
            np.testing.assert_allclose(x_pl, x_ref, rtol=2e-5, atol=2e-5)

    def test_fused_kernel_single_update_parity(self):
        """Tier-1 guard on the fused kernel itself: ONE whole-update launch
        (CPU interpreter) against the packed XLA assembly + solve, so the
        kernel path is exercised on every test run, not only on TPU —
        single update, no GN feedback, so tolerance stays tight.  NaN
        nodata rides under the mask exactly as ``io/warp.py`` produces
        it; p covers both real states (7 TIP, 10 PROSAIL)."""
        from kafka_tpu.core.linalg import solve_spd_packed, unpack_symmetric
        from kafka_tpu.core.pallas_solve import fused_update_pallas
        from kafka_tpu.core.solvers import build_normal_equations_packed

        for p in (7, 10):
            jac, h0, y, r_inv, mask, x_f, x_lin, p_inv = random_problem(
                n_pix=256, p=p, n_bands=2 if p == 7 else 10,
                mask_frac=0.3,
            )
            obs = BandBatch(
                y=jnp.asarray(np.where(mask, y, np.nan)),
                r_inv=jnp.asarray(np.where(mask, r_inv, 0.0)),
                mask=jnp.asarray(mask),
            )
            lin = Linearization(h0=jnp.asarray(h0), jac=jnp.asarray(jac))
            a_packed, b = build_normal_equations_packed(
                lin, obs, jnp.asarray(x_lin), jnp.asarray(x_f),
                jnp.asarray(p_inv),
            )
            x_ref = np.asarray(solve_spd_packed(a_packed, b))
            a_ref = np.asarray(unpack_symmetric(a_packed))
            x_pl, a_pl_packed = fused_update_pallas(
                lin, obs, jnp.asarray(x_lin), jnp.asarray(x_f),
                jnp.asarray(p_inv), interpret=True,
            )
            x_pl = np.asarray(x_pl)
            a_pl = np.asarray(unpack_symmetric(a_pl_packed))
            assert np.isfinite(x_pl).all(), f"p={p}: NaN leaked into x"
            assert np.isfinite(a_pl).all(), f"p={p}: NaN leaked into A"
            np.testing.assert_allclose(x_pl, x_ref, rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(a_pl, a_ref, rtol=1e-4, atol=1e-4)

    def test_use_pallas_nan_nodata_full_loop(self):
        """NaN nodata under a False mask (``io/warp.py`` default) must be
        inert through the WHOLE fused Gauss-Newton loop: selects, not
        mask multiplication (0 * NaN = NaN would poison every pixel).
        Asserts parity of the state, the information matrix AND the
        diagnostics against the XLA path fed the same NaN inputs."""
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(512, mask_prob=0.3)
        mask = np.asarray(bands.mask)
        y_nan = jnp.asarray(
            np.where(mask, np.asarray(bands.y), np.nan).astype(np.float32)
        )
        nan_bands = BandBatch(y=y_nan, r_inv=bands.r_inv, mask=bands.mask)
        opts = {"state_bounds": (
            jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
        )}
        x_ref, a_ref, d_ref = assimilate_date_jit(
            op.linearize, nan_bands, x0, p_inv0, None, opts
        )
        x_pl, a_pl, d_pl = assimilate_date_jit(
            op.linearize, nan_bands, x0, p_inv0, None,
            {**opts, "use_pallas": True},
        )
        x_pl, a_pl = np.asarray(x_pl), np.asarray(a_pl)
        assert np.isfinite(x_pl).all(), "NaN nodata leaked into the state"
        assert np.isfinite(a_pl).all(), "NaN nodata leaked into A"
        # GN-feedback tolerance, same reasoning as the parity test above.
        np.testing.assert_allclose(x_pl, np.asarray(x_ref), atol=2e-3)
        np.testing.assert_allclose(
            a_pl, np.asarray(a_ref), rtol=2e-2, atol=2e-2
        )
        assert int(d_pl.n_iterations) == int(d_ref.n_iterations)
        for field in ("innovations", "fwd_modelled"):
            got = np.asarray(getattr(d_pl, field))
            want = np.asarray(getattr(d_ref, field))
            assert np.isfinite(got).all(), f"NaN leaked into {field}"
            np.testing.assert_allclose(got, want, atol=5e-3,
                                       err_msg=field)

    @pytest.mark.slow
    def test_use_pallas_prosail_p10(self):
        """The fused path at the OTHER production state size: PROSAIL
        p=10, 10 bands, NaN nodata under the mask.  Slow-marked: the
        exact-SAIL jacfwd compile dominates (~80 s on the CPU mesh);
        tier-1 keeps p=10 kernel coverage via the fast
        ``test_fused_kernel_single_update_parity`` above."""
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.engine.priors import sail_prior
        from kafka_tpu.obsops.prosail import ProsailAux, ProsailOperator

        op = ProsailOperator()
        rng = np.random.default_rng(11)
        n_pix, p = 256, op.n_params
        prior = sail_prior().prior
        mean = np.asarray(prior.mean, np.float32)
        x0 = jnp.asarray(np.clip(
            mean + rng.normal(0, 0.02, (n_pix, p)), 0.02, 0.98
        ).astype(np.float32))
        p_inv0 = jnp.broadcast_to(
            jnp.asarray(np.asarray(prior.inv_cov, np.float32)),
            (n_pix, p, p),
        )
        aux = ProsailAux(sza=jnp.asarray(30.0), vza=jnp.asarray(5.0),
                         raa=jnp.asarray(90.0))
        h0 = np.asarray(op.linearize(aux, x0).h0)
        y = (h0 + rng.normal(0, 0.005, h0.shape)).astype(np.float32)
        mask = rng.uniform(size=y.shape) > 0.2
        bands = BandBatch(
            y=jnp.asarray(np.where(mask, y, np.nan).astype(np.float32)),
            r_inv=jnp.asarray(
                np.where(mask, 1 / 0.005**2, 0.0).astype(np.float32)
            ),
            mask=jnp.asarray(mask),
        )
        opts = {"state_bounds": (
            jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
        )}
        x_ref, a_ref, d_ref = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, aux, opts
        )
        x_pl, a_pl, d_pl = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, aux,
            {**opts, "use_pallas": True},
        )
        x_pl, a_pl = np.asarray(x_pl), np.asarray(a_pl)
        assert np.isfinite(x_pl).all() and np.isfinite(a_pl).all()
        np.testing.assert_allclose(x_pl, np.asarray(x_ref), atol=2e-3)
        np.testing.assert_allclose(
            a_pl, np.asarray(a_ref), rtol=2e-2, atol=2e-2
        )
        assert int(d_pl.n_iterations) == int(d_ref.n_iterations)

    def test_pallas_bounds_shapes(self):
        """Per-pixel (n_pix, p) bounds must clip identically on both
        paths (the row layout transposes them), and unsupported ranks
        must fail with a CLEAR error, not a while_loop carry-shape one."""
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(256)
        n_pix, p = x0.shape
        lo2d = jnp.broadcast_to(
            jnp.asarray(op.state_bounds[0]), (n_pix, p)
        )
        hi2d = jnp.broadcast_to(
            jnp.asarray(op.state_bounds[1]), (n_pix, p)
        )
        x_ref, _, d_ref = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, None,
            {"state_bounds": (lo2d, hi2d)},
        )
        x_pl, _, d_pl = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, None,
            {"state_bounds": (lo2d, hi2d), "use_pallas": True},
        )
        np.testing.assert_allclose(
            np.asarray(x_pl), np.asarray(x_ref), atol=2e-3
        )
        assert int(d_pl.n_iterations) == int(d_ref.n_iterations)
        with pytest.raises(ValueError, match="state_bounds"):
            assimilate_date_jit(
                op.linearize, bands, x0, p_inv0, None,
                {"state_bounds": (lo2d[..., None], hi2d[..., None]),
                 "use_pallas": True},
            )


class _QuadRowsOperator:
    """Minimal ``inkernel_linearize`` operator for configurable (p,
    n_bands): y_b = sum_k c[b,k] x_k^2 with the analytic lane-row
    Jacobian 2 c[b,k] x_k.  Implements exactly the
    ``ObservationModel`` surface the solver touches."""

    inkernel_linearize = True
    aux_per_pixel = True

    def __init__(self, coeff):
        self.coeff = np.asarray(coeff, np.float32)
        self.n_bands, self.n_params = self.coeff.shape
        self.state_bounds = (
            np.full(self.n_params, -10.0, np.float32),
            np.full(self.n_params, 10.0, np.float32),
        )

    def linearize(self, aux, x):
        c = jnp.asarray(self.coeff)
        return Linearization(
            h0=jnp.einsum("bp,np->bn", c, x**2),
            jac=2.0 * c[:, None, :] * x[None, :, :],
        )

    def kernel_linearize_rows(self, x_rows):
        p = self.n_params
        h0 = [
            sum(float(c[k]) * x_rows[k] ** 2 for k in range(p))
            for c in self.coeff
        ]
        jac = [
            [2.0 * float(c[k]) * x_rows[k] for k in range(p)]
            for c in self.coeff
        ]
        return h0, jac


class TestInKernelLinearize:
    """The in-kernel Gauss-Newton path (operator-advertised analytic
    linearisation, whole loop as ONE Pallas launch) against the XLA
    reference — the tentpole parity suite (p in {3, 7}, 1/2 bands)."""

    def _quad_problem(self, p, n_bands, n_pix=256, seed=0):
        rng = np.random.default_rng(seed)
        coeff = rng.uniform(0.5, 1.5, size=(n_bands, p)).astype(np.float32)
        op = _QuadRowsOperator(coeff)
        x_f = np.full((n_pix, p), 0.8, np.float32)
        x_true = x_f + rng.normal(0, 0.05, (n_pix, p)).astype(np.float32)
        y = np.einsum("bp,np->bn", coeff, x_true**2).astype(np.float32)
        mask = rng.uniform(size=y.shape) > 0.2
        r_inv = np.where(mask, 25.0, 0.0).astype(np.float32)
        # NaN nodata under the mask, exactly as io/warp.py produces it.
        bands = BandBatch(
            y=jnp.asarray(np.where(mask, y, np.nan).astype(np.float32)),
            r_inv=jnp.asarray(r_inv),
            mask=jnp.asarray(mask),
        )
        p_inv = np.broadcast_to(
            4.0 * np.eye(p, dtype=np.float32), (n_pix, p, p)
        ).copy()
        return op, bands, jnp.asarray(x_f), jnp.asarray(p_inv)

    def _parity(self, op, bands, x0, p_inv0, aux=None):
        from kafka_tpu.core.solvers import assimilate_date_jit

        opts = {"state_bounds": (
            jnp.asarray(op.state_bounds[0]),
            jnp.asarray(op.state_bounds[1]),
        )}
        x_ref, a_ref, d_ref = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, aux, opts
        )
        x_ik, a_ik, d_ik = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, aux,
            {**opts, "use_pallas": True, "inkernel_linearize": True},
        )
        x_ik_np, a_ik_np = np.asarray(x_ik), np.asarray(a_ik)
        assert np.isfinite(x_ik_np).all(), "NaN leaked into the state"
        assert np.isfinite(a_ik_np).all(), "NaN leaked into A"
        # The documented float32 GN-feedback tolerance (2e-3, BASELINE.md
        # "Roofline" numerics): the in-kernel accumulation order differs
        # from XLA's fusion schedule and the loop feeds it back.
        np.testing.assert_allclose(x_ik_np, np.asarray(x_ref), atol=2e-3)
        np.testing.assert_allclose(
            a_ik_np, np.asarray(a_ref), rtol=2e-2, atol=2e-2
        )
        assert int(d_ik.n_iterations) == int(d_ref.n_iterations)
        for field in ("innovations", "fwd_modelled"):
            got = np.asarray(getattr(d_ik, field))
            assert np.isfinite(got).all(), f"NaN leaked into {field}"
            np.testing.assert_allclose(
                got, np.asarray(getattr(d_ref, field)), atol=5e-3,
                err_msg=field,
            )

    @pytest.mark.parametrize("p,n_bands", [(3, 1), (3, 2), (7, 1)])
    def test_parity_quad_operator(self, p, n_bands):
        self._parity(*self._quad_problem(p, n_bands, seed=p * 10 + n_bands))

    def test_parity_twostream_p7_two_band(self):
        """The production TIP configuration (p=7, 2 bands) through the
        REAL operator's analytic in-kernel linearisation."""
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(512, mask_prob=0.2)
        self._parity(op, bands, x0, p_inv0)

    def test_twostream_kernel_rows_born_in_lane_layout(self):
        """Zero-relayout contract, asserted at the source: the
        operator's ``kernel_linearize_rows`` emits ``h0``/``jac`` rows
        DIRECTLY as lane vectors matching the batched ``linearize``
        transposed — there is no (B, n, p) tensor to relayout."""
        from kafka_tpu.obsops.twostream import TwoStreamOperator

        op = TwoStreamOperator()
        rng = np.random.default_rng(3)
        n, p = 64, op.n_params
        lo, hi = op.state_bounds
        x = (lo + (hi - lo) * rng.uniform(0.1, 0.9, (n, p))).astype(
            np.float32
        )
        x_rows = tuple(jnp.asarray(x[:, k]) for k in range(p))
        h0_rows, jac_rows = op.kernel_linearize_rows(x_rows)
        lin = op.linearize(None, jnp.asarray(x))
        for b in range(op.n_bands):
            assert h0_rows[b].shape == (n,), "h0 not a lane row"
            np.testing.assert_allclose(
                np.asarray(h0_rows[b]), np.asarray(lin.h0[b]), atol=1e-5
            )
            for k in range(p):
                assert jac_rows[b][k].shape == (n,), "jac not a lane row"
                np.testing.assert_allclose(
                    np.asarray(jac_rows[b][k]),
                    np.asarray(lin.jac[b, :, k]),
                    atol=1e-5, err_msg=f"band {b} dparam {k}",
                )

    def test_inkernel_jaxpr_has_no_jacobian_relayout(self):
        """The fused-kernel zero-relayout assertion at the program
        level: the in-kernel solve's jaxpr contains NO transpose of a
        rank-3 array (the (B, n, p) Jacobian and its (B*p, n) relayout
        never exist), while the out-of-kernel Pallas path — the positive
        control — contains at least one."""
        import jax

        from kafka_tpu.core.solvers import iterated_solve
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(256)

        def transposes_3d(closed):
            count = 0

            def walk(jaxpr):
                nonlocal count
                for eqn in jaxpr.eqns:
                    if eqn.primitive.name == "transpose" and \
                            eqn.invars[0].aval.ndim >= 3:
                        count += 1
                    for v in eqn.params.values():
                        vs = v if isinstance(v, (list, tuple)) else [v]
                        for item in vs:
                            inner = getattr(item, "jaxpr", None)
                            if inner is not None:
                                walk(inner)
                            elif hasattr(item, "eqns"):
                                walk(item)

            walk(closed.jaxpr)
            return count

        def make(inkernel):
            return jax.make_jaxpr(
                lambda b, x, pi: iterated_solve(
                    op.linearize, b, x, pi, None, use_pallas=True,
                    inkernel_linearize=inkernel,
                )
            )(bands, x0, p_inv0)

        assert transposes_3d(make(True)) == 0
        # Positive control: the out-of-kernel path relays the Jacobian
        # through the jac_to_rows shim — a 3-D transpose — every
        # iteration, so the counter cannot silently rot.
        assert transposes_3d(make(False)) > 0

    def test_nonempty_operator_params_fall_back(self):
        """Per-date aux keeps the out-of-kernel path (the in-kernel
        operators are closed-form); results stay correct either way."""
        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(256)
        opts = {"state_bounds": (
            jnp.asarray(op.state_bounds[0]),
            jnp.asarray(op.state_bounds[1]),
        )}
        aux = {"dummy": jnp.zeros((3,), jnp.float32)}
        x_ref, _, d_ref = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, aux, opts
        )
        x_pl, _, d_pl = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, aux,
            {**opts, "use_pallas": True},
        )
        np.testing.assert_allclose(
            np.asarray(x_pl), np.asarray(x_ref), atol=2e-3
        )
        assert int(d_pl.n_iterations) == int(d_ref.n_iterations)


class TestPerPixelConvergence:
    """solver option per_pixel_convergence (SURVEY §7(c)): converged
    pixels freeze at their fixed point instead of riding a global norm."""

    def _solve(self, n, per_pixel, sigma=0.03, relaxation=1.0, seed=0):
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(n, seed=seed, sigma=sigma)
        opts = {
            "state_bounds": (
                jnp.asarray(op.state_bounds[0]),
                jnp.asarray(op.state_bounds[1]),
            ),
            "relaxation": relaxation,
            "per_pixel_convergence": per_pixel,
        }
        x, p_inv, diags = assimilate_date_jit(
            op.linearize, bands, x0, p_inv0, None, opts
        )
        return op, bands, x0, p_inv0, np.asarray(x), diags

    def test_converged_mask_pixels_are_fixed_points(self):
        """Every pixel the solver reports frozen must be a Gauss-Newton
        fixed point of the ORIGINAL problem (prior still anchored at the
        forecast): one more true GN step moves it less than tol."""
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import kalman_update

        op, bands, x0, p_inv0, x, diags = self._solve(
            256, True, relaxation=0.7
        )
        frozen = np.asarray(diags.converged_mask)
        assert frozen.any(), "no pixel converged; test inconclusive"
        lin = op.linearize(None, jnp.asarray(x))
        x_new, _ = kalman_update(lin, bands, jnp.asarray(x),
                                 jnp.asarray(x0), p_inv0)
        x_new = jnp.asarray(x) + 0.7 * (x_new - jnp.asarray(x))
        x_new = jnp.clip(x_new, jnp.asarray(op.state_bounds[0]),
                         jnp.asarray(op.state_bounds[1]))
        step = np.sqrt(((np.asarray(x_new) - x) ** 2).sum(axis=-1)) / 7
        assert (step[frozen] < 2e-3).all(), step[frozen].max()

    def test_frozen_pixels_invariant_to_extra_iterations(self):
        """Once frozen, a pixel must not move however long the loop keeps
        running for its stiff neighbours."""
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import iterated_solve
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(256, sigma=0.03)
        common = dict(
            relaxation=0.7, per_pixel_convergence=True,
            state_bounds=(jnp.asarray(op.state_bounds[0]),
                          jnp.asarray(op.state_bounds[1])),
        )
        x_a, _, d_a = iterated_solve(
            op.linearize, bands, x0, p_inv0, None,
            max_iterations=10, **common
        )
        x_b, _, d_b = iterated_solve(
            op.linearize, bands, x0, p_inv0, None,
            max_iterations=25, **common
        )
        frozen_a = np.asarray(d_a.converged_mask)
        assert frozen_a.any()
        np.testing.assert_array_equal(
            np.asarray(x_a)[frozen_a], np.asarray(x_b)[frozen_a]
        )

    def test_global_mode_reports_no_mask(self):
        _, _, _, _, _, diags = self._solve(64, False)
        assert diags.converged_mask is None

    def test_stricter_than_global_norm(self):
        """The per-pixel criterion is strictly per pixel: the weak global
        norm (normalised by n*p, linear_kf.py:296) can declare a batch
        converged while individual pixels still move; per-pixel mode
        keeps iterating exactly those."""
        _, _, _, _, _, d_gl = self._solve(128, False, relaxation=0.7)
        _, _, _, _, _, d_pp = self._solve(128, True, relaxation=0.7)
        assert int(d_pp.n_iterations) >= int(d_gl.n_iterations)

    def test_all_masked_returns_forecast(self):
        import jax.numpy as jnp

        from kafka_tpu.core.solvers import assimilate_date_jit
        from kafka_tpu.core.types import BandBatch
        from kafka_tpu.testing.synthetic import make_tip_problem

        op, bands, x0, p_inv0 = make_tip_problem(64)
        zb = BandBatch(
            y=jnp.zeros_like(bands.y),
            r_inv=jnp.zeros_like(bands.r_inv),
            mask=jnp.zeros_like(bands.mask),
        )
        x, p_inv, _ = assimilate_date_jit(
            op.linearize, zb, x0, p_inv0, None,
            {"per_pixel_convergence": True},
        )
        np.testing.assert_allclose(np.asarray(x), np.asarray(x0),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(p_inv),
                                   np.asarray(p_inv0), atol=1e-4)
