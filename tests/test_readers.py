"""Reader + warp tests against synthetic on-disk fixture trees (SURVEY.md
§4: the reference's readers are only exercised by operator-run integration
scripts; these make them CI-testable)."""

import datetime
import os

import numpy as np
import pytest

from kafka_tpu.engine.state import make_pixel_gather
from kafka_tpu.io.geotiff import GeoInfo, write_geotiff
from kafka_tpu.io.modis import BHRObservations, TO_BHR
from kafka_tpu.io.sentinel1 import S1Observations
from kafka_tpu.io.sentinel2 import BAND_MAP, Sentinel2Observations
from kafka_tpu.io.warp import (
    lonlat_to_utm,
    reproject_raster,
    utm_to_lonlat,
)
from kafka_tpu.obsops import IdentityOperator, TwoStreamOperator

RNG = np.random.default_rng(7)


class TestWarp:
    def test_utm_roundtrip(self):
        lons = RNG.uniform(-3.2, -2.8, 50)
        lats = RNG.uniform(38.8, 39.3, 50)
        e, n = lonlat_to_utm(lons, lats, 32630)
        lon2, lat2 = utm_to_lonlat(e, n, 32630)
        np.testing.assert_allclose(lon2, lons, atol=1e-9)
        np.testing.assert_allclose(lat2, lats, atol=1e-9)

    def test_utm_known_point(self):
        # Madrid: 40.4168N 3.7038W -> zone 30N ~ (440290, 4474257)
        e, n = lonlat_to_utm(-3.7038, 40.4168, 32630)
        assert abs(e - 440290.5) < 1.0
        assert abs(n - 4474257.4) < 1.0

    def test_identity_warp(self):
        src = RNG.normal(size=(12, 9)).astype(np.float32)
        gt = (500000, 10, 0, 4000000, 0, -10)
        np.testing.assert_array_equal(
            reproject_raster(src, gt, (12, 9), gt), src
        )

    def test_shifted_grid_nearest(self):
        src = np.arange(64, dtype=np.float32).reshape(8, 8)
        gt = (0, 1, 0, 8, 0, -1)
        # destination = source shifted by exactly 2 px right/down
        dst_gt = (2, 1, 0, 6, 0, -1)
        out = reproject_raster(src, gt, (4, 4), dst_gt, nodata=-1)
        np.testing.assert_array_equal(out, src[2:6, 2:6])

    def test_bilinear_identity_keeps_edges(self):
        # A coincident-grid bilinear warp must reproduce the source
        # exactly, including the last row/column.
        src = RNG.normal(size=(8, 8)).astype(np.float32)
        gt = (0, 1, 0, 8, 0, -1)
        out = reproject_raster(src, gt, (8, 8), gt, method="bilinear",
                               nodata=-1)
        np.testing.assert_allclose(out, src, rtol=1e-6)

    def test_bilinear_multiband(self):
        src = RNG.normal(size=(8, 8, 3)).astype(np.float32)
        gt = (0, 1, 0, 8, 0, -1)
        out = reproject_raster(src, gt, (8, 8), gt, method="bilinear",
                               nodata=-1)
        assert out.shape == (8, 8, 3)
        np.testing.assert_allclose(out, src, rtol=1e-6)

    def test_cross_crs_bilinear_constant(self):
        # A constant field must stay constant under any reprojection.
        src = np.full((50, 50), 3.25, np.float32)
        src_gt = (570000, 10, 0, 4325000, 0, -10)
        lon_c, lat_c = utm_to_lonlat(570250, 4324750, 32630)
        dst_gt = (lon_c - 0.002, 0.0002, 0, lat_c + 0.0015, 0, -0.00015)
        out = reproject_raster(src, src_gt, (10, 10), dst_gt,
                               src_crs=32630, dst_crs=4326,
                               method="bilinear")
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, 3.25, rtol=1e-6)


_S2_XML = """<?xml version="1.0"?>
<root><Geo><Tile_Angles>
  <Mean_Sun_Angle>
    <ZENITH_ANGLE>30.5</ZENITH_ANGLE><AZIMUTH_ANGLE>150.0</AZIMUTH_ANGLE>
  </Mean_Sun_Angle>
  <Mean_Viewing_Incidence_Angle_List>
    <Mean_Viewing_Incidence_Angle bandId="0">
      <ZENITH_ANGLE>5.0</ZENITH_ANGLE><AZIMUTH_ANGLE>100.0</AZIMUTH_ANGLE>
    </Mean_Viewing_Incidence_Angle>
    <Mean_Viewing_Incidence_Angle bandId="1">
      <ZENITH_ANGLE>7.0</ZENITH_ANGLE><AZIMUTH_ANGLE>110.0</AZIMUTH_ANGLE>
    </Mean_Viewing_Incidence_Angle>
  </Mean_Viewing_Incidence_Angle_List>
</Tile_Angles></Geo></root>
"""

GT = (577000.0, 10.0, 0.0, 4323000.0, 0.0, -10.0)
NY, NX = 12, 16


def _make_s2_tree(root):
    gran = os.path.join(root, "2017", "7", "5", "S2A_GRANULE")
    os.makedirs(gran)
    geo = GeoInfo(geotransform=GT, epsg=32630)
    for b in BAND_MAP:
        refl = RNG.integers(500, 5000, (NY, NX)).astype(np.int32)
        refl[0, :] = 0  # a nodata row
        write_geotiff(os.path.join(gran, f"B{b}_sur.tif"),
                      refl.astype(np.float32), geo)
    write_geotiff(os.path.join(gran, "xxx_aot.tif"),
                  np.ones((NY, NX), np.float32), geo)
    with open(os.path.join(gran, "metadata.xml"), "w") as f:
        f.write(_S2_XML)
    return gran


class TestSentinel2:
    def test_discovery_and_band_data(self, tmp_path):
        _make_s2_tree(str(tmp_path))
        op = IdentityOperator(n_params=10,
                              obs_indices=tuple(range(10)))
        s2 = Sentinel2Observations(str(tmp_path), op, (GT, 32630))
        assert s2.dates == [datetime.datetime(2017, 7, 5)]
        assert s2.bands_per_observation[s2.dates[0]] == 10

        gather = make_pixel_gather(np.ones((NY, NX), bool), pad_multiple=64)
        obs = s2.get_observations(s2.dates[0], gather)
        y = np.asarray(obs.bands.y)
        mask = np.asarray(obs.bands.mask)
        r_inv = np.asarray(obs.bands.r_inv)
        assert y.shape == (10, gather.n_pad)
        # Scaling: reflectances in (0, 1]; nodata row masked out.
        assert (y[mask] > 0).all() and (y[mask] <= 0.5).all()
        nodata_pix = gather.gather(
            np.arange(NY * NX).reshape(NY, NX)
        ) < NX  # first raster row
        assert not mask[:, nodata_pix].any()
        # r_inv = 1/(0.05 y)^2 on valid pixels
        np.testing.assert_allclose(
            r_inv[mask], 1.0 / (0.05 * y[mask]) ** 2, rtol=1e-5
        )
        assert obs.aux["sza"] == 30.5
        assert obs.aux["vza"] == 6.0  # mean of 5 and 7

    def test_missing_folder_raises(self):
        with pytest.raises(IOError):
            Sentinel2Observations("/nonexistent/path",
                                  None, (GT, 32630))

    def test_geometry_bank_selection(self):
        from kafka_tpu.io.sentinel2 import (
            find_nearest_geometry,
            geometry_bank_aux_builder,
        )

        banks = {(30.0, 0.0, 50.0): "a", (30.0, 10.0, 50.0): "b",
                 (40.0, 10.0, 100.0): "c"}
        key = find_nearest_geometry(banks.keys(), 31.0, 9.0, 60.0)
        assert key == (30.0, 10.0, 50.0)
        build = geometry_bank_aux_builder(banks)
        meta = {"sza": 39.0, "vza": 11.0, "saa": 100.0, "vaa": 195.0}
        assert build(meta, None) == "c"


class TestS1EnlUncertainty:
    def test_enl_speckle_uncertainty(self, tmp_path):
        import h5py

        fname = "S1A_IW_GRDH_1SDV_pre_20170705T175515_y_z.nc"
        _make_s1_file(str(tmp_path / fname))
        gather = make_pixel_gather(np.ones((NY, NX), bool), pad_multiple=64)

        # constructor ENL wins; sigma^2 = y^2/L + nesz^2
        s1 = S1Observations(str(tmp_path), (GT, 32630), enl=4.4,
                            noise_floor=1e-3)
        obs = s1.get_observations(s1.dates[0], gather)
        y = np.asarray(obs.bands.y[0])
        r_inv = np.asarray(obs.bands.r_inv[0])
        mask = np.asarray(obs.bands.mask[0])
        expect = 1.0 / (y[mask] ** 2 / 4.4 + 1e-6)
        np.testing.assert_allclose(r_inv[mask], expect, rtol=1e-5)
        assert np.all(r_inv[~mask] == 0)

        # file-level enl attribute used when the constructor gives none
        with h5py.File(str(tmp_path / fname), "a") as f:
            f.attrs["enl"] = 9.0
        s1b = S1Observations(str(tmp_path), (GT, 32630))
        obs_b = s1b.get_observations(s1b.dates[0], gather)
        r_inv_b = np.asarray(obs_b.bands.r_inv[0])
        np.testing.assert_allclose(
            r_inv_b[mask], 9.0 / y[mask] ** 2, rtol=1e-5
        )

    def test_no_enl_keeps_relative_placeholder(self, tmp_path):
        fname = "S1A_IW_GRDH_1SDV_pre_20170705T175515_y_z.nc"
        _make_s1_file(str(tmp_path / fname))
        gather = make_pixel_gather(np.ones((NY, NX), bool), pad_multiple=64)
        s1 = S1Observations(str(tmp_path), (GT, 32630))
        obs = s1.get_observations(s1.dates[0], gather)
        y = np.asarray(obs.bands.y[0])
        mask = np.asarray(obs.bands.mask[0])
        np.testing.assert_allclose(
            np.asarray(obs.bands.r_inv[0])[mask],
            1.0 / (0.05 * y[mask]) ** 2, rtol=1e-5,
        )


class TestS1ThetaFallback:
    def test_missing_theta_defaults_to_23deg(self, tmp_path):
        import h5py

        fname = "S1A_IW_GRDH_1SDV_pre_20170705T175515_y_z.nc"
        with h5py.File(str(tmp_path / fname), "w") as f:
            for pol in ("VV", "VH"):
                f.create_dataset(
                    f"sigma0_{pol}",
                    data=RNG.uniform(0.01, 0.3, (NY, NX)).astype(np.float32),
                )
            f.attrs["geotransform"] = np.array(GT)
            f.attrs["epsg"] = 32630
        s1 = S1Observations(str(tmp_path), (GT, 32630))
        gather = make_pixel_gather(np.ones((NY, NX), bool), pad_multiple=64)
        obs = s1.get_observations(s1.dates[0], gather)
        np.testing.assert_allclose(np.asarray(obs.aux.theta_deg), 23.0)


def _make_s1_file(path):
    import h5py

    ny, nx = NY, NX
    with h5py.File(path, "w") as f:
        for pol in ("VV", "VH"):
            s0 = RNG.uniform(0.01, 0.3, (ny, nx)).astype(np.float32)
            s0[:, 0] = -999.0
            f.create_dataset(f"sigma0_{pol}", data=s0)
        f.create_dataset(
            "theta", data=np.full((ny, nx), 37.5, np.float32)
        )
        f.attrs["geotransform"] = np.array(GT)
        f.attrs["epsg"] = 32630


class TestSentinel1:
    def test_discovery_and_band_data(self, tmp_path):
        # date in filename field 5, the reference's convention
        # (Sentinel1_Observations.py:74-78)
        fname = "S1A_IW_GRDH_1SDV_pre_20170705T175515_y_z.nc"
        _make_s1_file(str(tmp_path / fname))
        s1 = S1Observations(str(tmp_path), (GT, 32630))
        assert s1.dates == [datetime.datetime(2017, 7, 5, 17, 55, 15)]

        gather = make_pixel_gather(np.ones((NY, NX), bool), pad_multiple=64)
        obs = s1.get_observations(s1.dates[0], gather)
        y = np.asarray(obs.bands.y)
        mask = np.asarray(obs.bands.mask)
        assert y.shape == (2, gather.n_pad)
        # -999 column masked
        col0 = gather.gather(
            np.tile(np.arange(NX), (NY, 1))
        ) == 0
        assert not mask[:, col0].any()
        assert mask[:, ~col0 & gather.valid].all()
        # incidence angle rides aux
        theta = np.asarray(obs.aux.theta_deg)
        np.testing.assert_allclose(theta[gather.valid], 37.5)


def _make_modis_dir(root, dates):
    geo = GeoInfo(geotransform=GT, epsg=32630)
    truth = {}
    for d in dates:
        stem = f"MCD43_A{d.strftime('%Y%j')}"
        for band in ("vis", "nir"):
            k = RNG.uniform(0.0, 0.5, (NY, NX, 3)).astype(np.float32)
            qa = np.zeros((NY, NX), np.uint8)
            qa[:, -2:] = 1     # magnitude inversion
            qa[0, :] = 255     # fill
            write_geotiff(os.path.join(root, f"{stem}_{band}_kernels.tif"),
                          k, geo)
            write_geotiff(os.path.join(root, f"{stem}_{band}_qa.tif"),
                          qa, geo)
            truth[(d, band)] = (k, qa)
    return truth


class TestMODIS:
    def test_thinning_and_band_data(self, tmp_path):
        dates = [
            datetime.datetime(2017, 1, 1) + datetime.timedelta(days=i)
            for i in range(0, 48)
        ]
        truth = _make_modis_dir(str(tmp_path), dates)
        op = TwoStreamOperator()
        bhr = BHRObservations(str(tmp_path), op, period=16)
        assert len(bhr.dates) == 3  # 48 days thinned by 16

        gather = make_pixel_gather(np.ones((NY, NX), bool), pad_multiple=64)
        obs = bhr.get_observations(bhr.dates[0], gather)
        y = np.asarray(obs.bands.y)
        mask = np.asarray(obs.bands.mask)
        r_inv = np.asarray(obs.bands.r_inv)
        assert y.shape == (2, gather.n_pad)
        k, qa = truth[(bhr.dates[0], "vis")]
        expected = (k.reshape(-1, 3) @ TO_BHR).astype(np.float32)
        qa_flat = qa.reshape(-1)
        valid = qa_flat <= 1
        np.testing.assert_allclose(
            y[0, : NY * NX][valid], expected[valid], rtol=1e-5
        )
        assert not mask[:, : NY * NX][:, qa_flat == 255].any()
        # QA 1 pixels get the 7% sigma
        qa1 = (qa_flat == 1) & (expected > 2.5e-3 / 0.07)
        if qa1.any():
            np.testing.assert_allclose(
                r_inv[0, : NY * NX][qa1],
                1.0 / np.maximum(2.5e-3, expected[qa1] * 0.07) ** 2,
                rtol=1e-4,
            )

    def test_roi_window(self, tmp_path):
        dates = [datetime.datetime(2017, 1, 1)]
        _make_modis_dir(str(tmp_path), dates)
        bhr = BHRObservations(str(tmp_path), TwoStreamOperator(), period=1)
        bhr.apply_roi(2, 1, 10, 7)
        gather = make_pixel_gather(np.ones((6, 8), bool), pad_multiple=64)
        obs = bhr.get_observations(bhr.dates[0], gather)
        assert np.asarray(obs.bands.y).shape == (2, gather.n_pad)


class TestParseS2Xml:
    def test_missing_sun_angle_raises(self, tmp_path):
        p = tmp_path / "metadata.xml"
        p.write_text("<root><Tile_Angles></Tile_Angles></root>")
        from kafka_tpu.io.sentinel2 import parse_s2_xml

        with pytest.raises(ValueError, match="Mean_Sun_Angle"):
            parse_s2_xml(str(p))

    def test_missing_viewing_angles_raises(self, tmp_path):
        p = tmp_path / "metadata.xml"
        p.write_text(
            "<root><Tile_Angles><Mean_Sun_Angle>"
            "<ZENITH_ANGLE>30</ZENITH_ANGLE><AZIMUTH_ANGLE>150</AZIMUTH_ANGLE>"
            "</Mean_Sun_Angle></Tile_Angles></root>"
        )
        from kafka_tpu.io.sentinel2 import parse_s2_xml

        with pytest.raises(ValueError, match="Viewing"):
            parse_s2_xml(str(p))


class TestS1AutoEnl:
    def test_estimator_recovers_known_looks(self):
        """Gamma-speckled intensity with known L: the moments estimator
        over homogeneous blocks must recover L within ~20%."""
        from kafka_tpu.io.sentinel1 import estimate_enl

        rng = np.random.default_rng(5)
        L = 5.0
        truth = 0.08  # homogeneous scene
        arr = truth * rng.gamma(L, 1.0 / L, (140, 140))
        est = estimate_enl(arr.astype(np.float32))
        assert est is not None
        assert abs(est - L) / L < 0.2, est

    def test_estimator_robust_to_texture(self):
        """Half the scene strongly textured: the high-quantile block
        statistic must still track the true L from the homogeneous half
        (texture only biases ENL low)."""
        from kafka_tpu.io.sentinel1 import estimate_enl

        rng = np.random.default_rng(6)
        L = 8.0
        base = np.full((140, 140), 0.1)
        base[:, 70:] *= rng.uniform(0.2, 3.0, (140, 70))  # texture
        arr = base * rng.gamma(L, 1.0 / L, base.shape)
        est = estimate_enl(arr.astype(np.float32))
        assert est is not None
        assert abs(est - L) / L < 0.35, est

    def test_auto_mode_drives_r_inv(self, tmp_path):
        """enl='auto': per-scene estimate feeds sigma^2 = y^2/ENL_hat."""
        import h5py

        fname = "S1A_IW_GRDH_1SDV_pre_20170705T175515_y_z.nc"
        rng = np.random.default_rng(7)
        L = 6.0
        ny = nx = 70
        gt = (GT[0], GT[1], 0.0, GT[3], 0.0, GT[5])
        with h5py.File(str(tmp_path / fname), "w") as f:
            for pol in ("VV", "VH"):
                s0 = (0.1 * rng.gamma(L, 1.0 / L, (ny, nx))).astype(
                    np.float32
                )
                f.create_dataset(f"sigma0_{pol}", data=s0)
            f.attrs["geotransform"] = np.array(gt)
            f.attrs["epsg"] = 32630
        gather = make_pixel_gather(np.ones((ny, nx), bool),
                                   pad_multiple=64)
        s1 = S1Observations(str(tmp_path), (gt, 32630), enl="auto")
        obs = s1.get_observations(s1.dates[0], gather)
        y = np.asarray(obs.bands.y[0])
        mask = np.asarray(obs.bands.mask[0])
        r_inv = np.asarray(obs.bands.r_inv[0])
        est = s1._enl_cache[("auto", s1.date_data[s1.dates[0]])]
        assert est is not None and abs(est - L) / L < 0.35
        np.testing.assert_allclose(
            r_inv[mask], est / y[mask] ** 2, rtol=1e-4
        )

    def test_auto_mode_falls_back_when_unestimable(self, tmp_path):
        """A scene too small for block statistics keeps the reference's
        relative placeholder."""
        import h5py

        fname = "S1A_IW_GRDH_1SDV_pre_20170705T175515_y_z.nc"
        ny = nx = 5  # smaller than one estimation block
        gt = (GT[0], GT[1], 0.0, GT[3], 0.0, GT[5])
        with h5py.File(str(tmp_path / fname), "w") as f:
            for pol in ("VV", "VH"):
                f.create_dataset(
                    f"sigma0_{pol}",
                    data=np.full((ny, nx), 0.1, np.float32),
                )
            f.attrs["geotransform"] = np.array(gt)
            f.attrs["epsg"] = 32630
        gather = make_pixel_gather(np.ones((ny, nx), bool),
                                   pad_multiple=32)
        s1 = S1Observations(str(tmp_path), (gt, 32630), enl="auto")
        obs = s1.get_observations(s1.dates[0], gather)
        y = np.asarray(obs.bands.y[0])
        mask = np.asarray(obs.bands.mask[0])
        np.testing.assert_allclose(
            np.asarray(obs.bands.r_inv[0])[mask],
            1.0 / (0.05 * y[mask]) ** 2, rtol=1e-5,
        )


class TestGeometryBankFallback:
    def test_disagreeing_axes_pick_existing_key(self):
        """Incomplete bank: each axis's nearest grid value exists but
        their combination is no actual key — the fallback must return an
        EXISTING key, never fabricate the per-axis combination."""
        from kafka_tpu.io.sentinel2 import find_nearest_geometry

        banks = {
            (20.0, 0.0, 50.0): "a",
            (40.0, 10.0, 120.0): "b",
        }
        # per-axis nearest: sza->40, vza->0, raa->50 — not a key
        key = find_nearest_geometry(banks.keys(), 38.0, 2.0, 55.0)
        assert key in banks
        # normalised distance: d(a) = 18/20 + 2/10 + 5/70 ~ 1.17,
        # d(b) = 2/20 + 8/10 + 65/70 ~ 1.83 -> "a"
        assert banks[key] == "a"

    def test_span_normalisation_prevents_raa_dominance(self):
        """With raw degrees the wide raa axis would decide alone; the
        span-normalised metric weights axes comparably."""
        from kafka_tpu.io.sentinel2 import find_nearest_geometry

        banks = {
            (20.0, 0.0, 170.0): "near_in_raw_raa",
            (42.0, 8.0, 10.0): "near_in_zeniths",
        }
        # query close to the second key in zeniths, far in raa
        key = find_nearest_geometry(banks.keys(), 40.0, 7.0, 90.0)
        # raw L1: first = 20+7+80=107, second = 2+1+80=83 -> second;
        # normalised: first = 20/22+7/8+80/160 = 2.28,
        #             second = 2/22+1/8+80/160 = 0.72 -> second, robustly
        assert banks[key] == "near_in_zeniths"

    def test_exact_grid_still_wins(self):
        from kafka_tpu.io.sentinel2 import find_nearest_geometry

        banks = {(30.0, 0.0, 50.0): 1, (30.0, 10.0, 90.0): 2}
        assert find_nearest_geometry(banks.keys(), 29.0, 9.0, 88.0) == \
            (30.0, 10.0, 90.0)


class TestS2BandPool:
    def test_parallel_band_reads_match_serial(self, tmp_path):
        """band_workers>1 threads the 10 read->decode->warp->gather chains
        per date; outputs must be identical to the serial loop."""
        import datetime as _dt

        from kafka_tpu.testing.fixtures import (
            DEFAULT_GEO, make_s2_granule_tree,
        )

        dates = [_dt.datetime(2017, 7, 1), _dt.datetime(2017, 7, 3)]
        make_s2_granule_tree(str(tmp_path / "s2"), dates, ny=40, nx=30)
        gather = make_pixel_gather(np.ones((40, 30), bool),
                                   pad_multiple=64)
        geo = (DEFAULT_GEO.geotransform, DEFAULT_GEO.epsg)
        serial = Sentinel2Observations(
            str(tmp_path / "s2"), None, geo, band_workers=1
        )
        pooled = Sentinel2Observations(
            str(tmp_path / "s2"), None, geo, band_workers=4
        )
        assert pooled.band_workers == 4
        for d in dates:
            a = serial.get_observations(d, gather)
            b = pooled.get_observations(d, gather)
            np.testing.assert_array_equal(
                np.asarray(a.bands.y), np.asarray(b.bands.y)
            )
            np.testing.assert_array_equal(
                np.asarray(a.bands.r_inv), np.asarray(b.bands.r_inv)
            )
            np.testing.assert_array_equal(
                np.asarray(a.bands.mask), np.asarray(b.bands.mask)
            )


class TestGatheredWarpCacheIsolation:
    def test_one_reader_many_gathers(self, tmp_path):
        """One reader serving DIFFERENT PixelGathers (the public API
        allows it) must keep their cached warp coordinates isolated —
        guards the id-keyed coordinate cache against collisions."""
        import datetime as _dt

        from kafka_tpu.testing.fixtures import (
            DEFAULT_GEO, make_s2_granule_tree,
        )

        dates = [_dt.datetime(2017, 7, 1)]
        make_s2_granule_tree(str(tmp_path / "s2"), dates, ny=30, nx=30,
                             noise=0.01)
        geo = (DEFAULT_GEO.geotransform, DEFAULT_GEO.epsg)
        src = Sentinel2Observations(str(tmp_path / "s2"), None, geo,
                                    band_workers=1)
        m_a = np.zeros((30, 30), bool)
        m_a[:10] = True
        m_b = np.zeros((30, 30), bool)
        m_b[20:] = True
        g_a = make_pixel_gather(m_a, 64)
        g_b = make_pixel_gather(m_b, 64)
        o_a = src.get_observations(dates[0], g_a)
        o_b = src.get_observations(dates[0], g_b)
        o_a2 = src.get_observations(dates[0], g_a)
        np.testing.assert_array_equal(
            np.asarray(o_a.bands.y), np.asarray(o_a2.bands.y)
        )
        assert not np.allclose(
            np.asarray(o_a.bands.y), np.asarray(o_b.bands.y)
        )
        # parity with a cold-cache reader for the second gather
        fresh = Sentinel2Observations(str(tmp_path / "s2"), None, geo,
                                      band_workers=1)
        o_b2 = fresh.get_observations(dates[0], g_b)
        np.testing.assert_array_equal(
            np.asarray(o_b.bands.y), np.asarray(o_b2.bands.y)
        )


def test_estimate_enl_trailing_band_axis():
    """A (ny, nx, 1) sigma0 layout (io.warp's trailing band axis) must
    estimate like its 2-D squeeze; deeper stacks return None (fallback)."""
    from kafka_tpu.io.sentinel1 import estimate_enl

    rng = np.random.default_rng(8)
    L = 6.0
    arr2d = (0.1 * rng.gamma(L, 1.0 / L, (120, 120))).astype(np.float32)
    est2d = estimate_enl(arr2d)
    est3d = estimate_enl(arr2d[..., None])
    assert est2d is not None and est3d == est2d
    assert estimate_enl(np.zeros((4, 5, 6, 7), np.float32)) is None
