"""Engine-path mesh execution: ``KalmanFilter(mesh=...)``.

The production gap closed in round 3 (VERDICT r2 Missing #1): the engine
itself — not just ``shard.step`` — must partition every per-date program
over the pixel mesh.  These tests prove on the virtual 8-device CPU mesh
that (a) the sharded engine run equals the single-device run to float
tolerance, on both the unfused and the temporally-fused (lax.scan) paths,
and (b) the pixel axis is genuinely partitioned across all devices.
"""

import datetime

import jax.numpy as jnp
import numpy as np

from kafka_tpu.core.propagators import PixelPrior
from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
from kafka_tpu.obsops import WCMOperator
from kafka_tpu.obsops.wcm import WCMAux
from kafka_tpu.shard import make_pixel_mesh
from kafka_tpu.testing import MemoryOutput, SyntheticObservations
from kafka_tpu.testing.synthetic import run_tip_engine


def day(i):
    return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)


def circle_mask(ny=12, nx=14, r=5):
    yy, xx = np.mgrid[:ny, :nx]
    return (yy - ny / 2) ** 2 + (xx - nx / 2) ** 2 < r**2


class TestEngineMeshParity:
    def test_sharded_run_matches_single_device(self, eight_cpu_devices):
        """Unfused path: per-date programs partitioned under GSPMD must
        reproduce the unsharded engine to float tolerance."""
        obs_days, grid_days = (1, 2, 4, 5), (0, 3, 6)
        mesh = make_pixel_mesh(eight_cpu_devices)
        kf_s, out_s, x_s, pinv_s = run_tip_engine(
            mesh, 1, obs_days, grid_days
        )
        kf_r, out_r, x_r, pinv_r = run_tip_engine(
            None, 1, obs_days, grid_days
        )
        assert sorted(out_s.output) == sorted(out_r.output)
        for ts in out_r.output:
            for key in out_r.output[ts]:
                np.testing.assert_allclose(
                    out_s.output[ts][key], out_r.output[ts][key],
                    atol=2e-4, err_msg=f"{ts} {key}",
                )
        np.testing.assert_allclose(
            np.asarray(x_s)[: x_r.shape[0]], np.asarray(x_r), atol=2e-4
        )

    def test_fused_sharded_matches_unfused_single_device(
        self, eight_cpu_devices
    ):
        """Temporal fusion + mesh compose (VERDICT r2 Missing #3): the
        fused-sharded run equals the unfused single-device run."""
        # Single-obs windows so the fused block forms: obs on 1,3,5,7 with
        # grid 0,2,4,6,8 -> four consecutive fusable windows.
        obs_days, grid_days = (1, 3, 5, 7), (0, 2, 4, 6, 8)
        mesh = make_pixel_mesh(eight_cpu_devices)
        kf_s, out_s, x_s, _ = run_tip_engine(mesh, 4, obs_days, grid_days)
        kf_r, out_r, x_r, _ = run_tip_engine(None, 1, obs_days, grid_days)
        assert any(
            rec.get("fused") for rec in kf_s.diagnostics_log
        ), "the sharded run should have taken the fused path"
        for ts in out_r.output:
            for key in out_r.output[ts]:
                np.testing.assert_allclose(
                    out_s.output[ts][key], out_r.output[ts][key],
                    atol=3e-4, err_msg=f"{ts} {key}",
                )
        np.testing.assert_allclose(
            np.asarray(x_s)[: x_r.shape[0]], np.asarray(x_r), atol=3e-4
        )

    def test_state_actually_partitioned(self, eight_cpu_devices):
        mesh = make_pixel_mesh(eight_cpu_devices)
        kf, out, x_a, p_inv_a = run_tip_engine(mesh, 1, (1, 2), (0, 3))
        assert len(x_a.sharding.device_set) == 8
        n_pad = kf.gather.n_pad
        assert n_pad % 8 == 0
        rows = {s.data.shape[0] for s in x_a.addressable_shards}
        assert rows == {n_pad // 8}
        assert len(p_inv_a.sharding.device_set) == 8

    def test_per_pixel_aux_is_sharded(self, eight_cpu_devices):
        """Per-pixel aux leaves (SAR incidence angles) must split on the
        pixel axis, not replicate."""
        mesh = make_pixel_mesh(eight_cpu_devices)
        mask = circle_mask()
        op = WCMOperator()
        truth = np.full(mask.shape + (2,), 0.0, np.float32)
        truth[..., 0] = 2.0   # LAI
        truth[..., 1] = 0.25  # SM

        def aux_fn(date, gather):
            theta = 20.0 + 15.0 * np.linspace(
                0.0, 1.0, gather.n_pad
            ).astype(np.float32)
            return WCMAux(theta_deg=jnp.asarray(theta))

        def build(mesh):
            obs = SyntheticObservations(
                dates=[day(1), day(2)], operator=op,
                truth_fn=lambda date: truth, sigma=0.1,
                aux_fn=aux_fn, mask_prob=0.0,
            )
            out = MemoryOutput()
            prior = FixedGaussianPrior(
                PixelPrior(
                    mean=jnp.asarray([1.0, 0.2], jnp.float32),
                    cov=jnp.asarray(np.diag([1.0, 0.01]), jnp.float32),
                    inv_cov=jnp.asarray(
                        np.diag([1.0, 100.0]), jnp.float32
                    ),
                ),
                ("LAI", "SM"),
            )
            kf = KalmanFilter(
                obs, out, mask, ("LAI", "SM"),
                state_propagation=None, prior=prior, pad_multiple=64,
                scan_window=1, mesh=mesh, mesh_lane=8,
            )
            kf.set_trajectory_uncertainty(np.zeros(2))
            x0, p_inv0 = prior.process_prior(None, kf.gather)
            x_a, _, _ = kf.run([day(0), day(3)], x0, None, p_inv0)
            return kf, x_a

        kf_s, x_s = build(mesh)
        kf_r, x_r = build(None)
        assert len(x_s.sharding.device_set) == 8
        np.testing.assert_allclose(
            np.asarray(x_s)[: x_r.shape[0]], np.asarray(x_r), atol=2e-4
        )


def test_single_device_mesh_works(eight_cpu_devices):
    """device_mesh='local' forced on a one-chip host: a 1-device mesh
    must run and match the no-mesh path exactly (guards the forced-local
    configuration on single-chip machines)."""
    mesh = make_pixel_mesh(eight_cpu_devices[:1])
    kf_s, out_s, x_s, _ = run_tip_engine(mesh, 1, (1, 2), (0, 3))
    kf_r, out_r, x_r, _ = run_tip_engine(None, 1, (1, 2), (0, 3))
    assert kf_s.gather.n_pad == kf_r.gather.n_pad
    np.testing.assert_allclose(
        np.asarray(x_s), np.asarray(x_r), atol=1e-6
    )
