"""gp_emulator pickle ingestion: the reference's emulator artifacts must
convert into GPParams without the gp_emulator package installed, with the
converted predictive mean matching the original formulation exactly.
"""

import pickle
import sys
import types

import numpy as np
import pytest

from kafka_tpu.obsops import GPBankOperator
from kafka_tpu.obsops.gp import gp_predict_pixel
from kafka_tpu.obsops.gp_import import (
    geometry_from_filename,
    gp_params_from_emulator,
    load_emulator_bank_file,
    load_emulator_directory,
    load_emulator_pickle,
)

RNG = np.random.default_rng(17)


def _reference_predict(inputs, invQt, theta, x_star):
    """The gp_emulator predictive mean, re-derived: a @ invQt with
    a_j = e^{theta[D]} exp(-0.5 sum_d e^{theta[d]} (x*_d - X_jd)^2)."""
    d = inputs.shape[1]
    w = np.exp(theta[:d])
    diff = inputs - x_star
    a = np.exp(theta[d]) * np.exp(-0.5 * (w * diff**2).sum(axis=1))
    return float(a @ invQt)


def _fake_module():
    """ONE fake gp_emulator module/class pair for the whole test run —
    pickling by reference requires every instance to share the class
    object registered in sys.modules at dump time."""
    if not hasattr(_fake_module, "_mod"):
        mod = types.ModuleType("gp_emulator")

        class GaussianProcess:
            pass

        GaussianProcess.__module__ = "gp_emulator"
        GaussianProcess.__qualname__ = "GaussianProcess"
        mod.GaussianProcess = GaussianProcess
        _fake_module._mod = mod
    return _fake_module._mod


def _make_fake_gp(m=40, d=4, seed=0, with_invqt=True):
    """An object pickled AS a gp_emulator.GaussianProcess: the class is
    registered under a fake gp_emulator module for pickling, then the
    module is removed so loading must work without it."""
    rng = np.random.default_rng(seed)
    inputs = rng.uniform(0.0, 1.0, (m, d)).astype(np.float64)
    targets = np.sin(inputs.sum(axis=1)) + 0.05 * rng.standard_normal(m)
    # theta = [log inverse-sq lengthscales..., log amp, log noise]
    theta = np.concatenate([
        np.log(rng.uniform(2.0, 20.0, d)),
        [np.log(1.3)], [np.log(1e-4)],
    ])
    w = np.exp(theta[:d])
    z = inputs * np.sqrt(w)
    d2 = (z * z).sum(1)[:, None] + (z * z).sum(1)[None, :] - 2 * z @ z.T
    k = np.exp(theta[d]) * np.exp(-0.5 * np.maximum(d2, 0.0))
    k[np.diag_indices_from(k)] += np.exp(theta[d + 1])
    invQt = np.linalg.solve(k, targets)

    mod = _fake_module()
    gp = mod.GaussianProcess()
    gp.inputs = inputs
    gp.targets = targets
    gp.theta = theta
    if with_invqt:
        gp.invQt = invQt
    return gp, mod, (inputs, invQt, theta)


def _pickle_without_module(obj, mod, path):
    sys.modules["gp_emulator"] = mod
    try:
        with open(path, "wb") as f:
            pickle.dump(obj, f, protocol=2)
    finally:
        del sys.modules["gp_emulator"]
    assert "gp_emulator" not in sys.modules


class TestEmulatorConversion:
    def test_predictive_mean_matches_reference_formula(self, tmp_path):
        gp, mod, (inputs, invQt, theta) = _make_fake_gp()
        path = str(tmp_path / "emu.pkl")
        _pickle_without_module(gp, mod, path)

        loaded = load_emulator_pickle(path)
        params = gp_params_from_emulator(loaded)
        for i in range(5):
            x_star = RNG.uniform(0.0, 1.0, inputs.shape[1]).astype(
                np.float32
            )
            ours = float(gp_predict_pixel(params, x_star))
            ref = _reference_predict(inputs, invQt, theta, x_star)
            np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_missing_invqt_recomputed(self, tmp_path):
        gp, mod, (inputs, invQt, theta) = _make_fake_gp(with_invqt=False)
        path = str(tmp_path / "emu.pkl")
        _pickle_without_module(gp, mod, path)
        params = gp_params_from_emulator(load_emulator_pickle(path))
        x_star = RNG.uniform(0.0, 1.0, inputs.shape[1]).astype(np.float32)
        np.testing.assert_allclose(
            float(gp_predict_pixel(params, x_star)),
            _reference_predict(inputs, invQt, theta, x_star),
            rtol=1e-3, atol=1e-3,
        )

    def test_band_dict_to_bank_and_operator(self, tmp_path):
        """The reference's artifact shape: dict keyed b'S2A_MSI_NN', one
        GP per band, differing inducing-set sizes — must stack into a
        GPBankOperator aux whose forward matches each band's GP."""
        bank = {}
        originals = {}
        mod = None
        band_numbers = (2, 3, 4, 5, 6, 7, 8, 9, 12, 13)
        for i, num in enumerate(band_numbers):
            gp, mod, arrs = _make_fake_gp(m=30 + 3 * i, seed=num)
            bank[b"S2A_MSI_%02d" % num] = gp
            originals[num] = arrs
        path = str(tmp_path / "prosail_5_30_90.pkl")
        _pickle_without_module(bank, mod, path)

        stacked = load_emulator_bank_file(path)
        assert stacked.x_train.shape[0] == len(band_numbers)
        op = GPBankOperator(n_params=4, n_bands=len(band_numbers))
        x_star = RNG.uniform(0.2, 0.8, 4).astype(np.float32)
        got = np.asarray(op.forward_pixel(stacked, x_star))
        want = np.array([
            _reference_predict(*originals[num], x_star)
            for num in band_numbers
        ])
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_incomplete_band_dict_raises(self, tmp_path):
        gp, mod, _ = _make_fake_gp()
        path = str(tmp_path / "emu_5_30_90.pkl")
        _pickle_without_module({b"S2A_MSI_02": gp}, mod, path)
        with pytest.raises(KeyError, match="band"):
            load_emulator_bank_file(path)

    def test_geometry_filename_parse(self):
        # reference convention: ..._{vza}_{sza}_{raa}.pkl
        # (vza third-from-last, sza second, raa last)
        sza, vza, raa = geometry_from_filename(
            "/x/prosail_S2A_10_30_120.pkl"
        )
        assert (sza, vza, raa) == (30.0, 10.0, 120.0)
        with pytest.raises(ValueError):
            geometry_from_filename("/x/no_geometry_here.pkl")

    def test_directory_to_geometry_banks(self, tmp_path):
        gp, mod, _ = _make_fake_gp()
        band_numbers = (2, 3)
        for vza, sza, raa in ((0, 20, 50), (10, 40, 120)):
            bank = {}
            for num in band_numbers:
                g, mod, _ = _make_fake_gp(m=20, seed=num)
                bank[b"S2A_MSI_%02d" % num] = g
            _pickle_without_module(
                bank, mod,
                str(tmp_path / f"prosail_{vza}_{sza}_{raa}.pkl"),
            )
        banks = load_emulator_directory(
            str(tmp_path), band_numbers=band_numbers
        )
        assert set(banks) == {(20.0, 0.0, 50.0), (40.0, 10.0, 120.0)}
        # drops into the S2 geometry selection unchanged
        from kafka_tpu.io.sentinel2 import geometry_bank_aux_builder

        build = geometry_bank_aux_builder(banks)
        meta = {"sza": 38.0, "vza": 11.0, "saa": 10.0, "vaa": 128.0}
        aux = build(meta, None)
        assert aux.x_train.shape[0] == len(band_numbers)


class TestBankPrecedenceAndValidation:
    def test_npz_wins_over_pickle_for_same_geometry(self, tmp_path):
        import jax.numpy as jnp

        from kafka_tpu.obsops.gp import GPParams
        from kafka_tpu.obsops.gp_import import (
            load_emulator_directory, save_bank_npz,
        )

        gp, mod, _ = _make_fake_gp(m=12)
        _pickle_without_module(
            {b"S2A_MSI_02": gp},
            mod, str(tmp_path / "bank_5_30_90.pkl"),
        )
        marker = GPParams(
            x_train=jnp.zeros((1, 7, 4)), alpha=jnp.ones((1, 7)),
            log_lengthscales=jnp.zeros((1, 4)),
            log_amplitude=jnp.zeros((1,)), y_mean=jnp.full((1,), 42.0),
        )
        save_bank_npz(str(tmp_path / "bank_5_30_90.npz"), marker)
        banks = load_emulator_directory(str(tmp_path),
                                        band_numbers=(2,))
        assert float(banks[(30.0, 5.0, 90.0)].y_mean[0]) == 42.0

    def test_bank_band_mismatch_raises_not_clamps(self):
        import jax.numpy as jnp

        from kafka_tpu.obsops.gp import GPBankOperator, GPParams

        bank = GPParams(
            x_train=jnp.zeros((3, 6, 4)), alpha=jnp.zeros((3, 6)),
            log_lengthscales=jnp.zeros((3, 4)),
            log_amplitude=jnp.zeros((3,)), y_mean=jnp.zeros((3,)),
        )
        op = GPBankOperator(n_params=4, n_bands=10)
        with pytest.raises(ValueError, match="3 band"):
            op.forward_pixel(bank, jnp.zeros(4))

    def test_driver_cache_written_once(self, tmp_path):
        from kafka_tpu.cli import drivers

        gp, mod, _ = _make_fake_gp(m=10)
        _pickle_without_module(
            {b"S2A_MSI_%02d" % n: gp for n in (2, 3)},
            mod, str(tmp_path / "bank_0_20_50.pkl"),
        )
        drivers._emulator_banks.cache_clear()
        import kafka_tpu.obsops.gp_import as gpi

        orig = gpi.load_emulator_bank_file
        calls = []

        def counting(path, **kw):
            calls.append(path)
            return orig(path, band_numbers=(2, 3))

        gpi.load_emulator_bank_file = counting
        try:
            banks1 = drivers._emulator_banks(str(tmp_path))
            assert len(calls) == 1
            assert (tmp_path / ".kafka_tpu_banks").is_dir()
            # A FRESH process (simulated via cache_clear) loads the npz
            # cache, not the pickle.
            drivers._emulator_banks.cache_clear()
            banks2 = drivers._emulator_banks(str(tmp_path))
            assert len(calls) == 1  # pickle not touched again
        finally:
            gpi.load_emulator_bank_file = orig
            drivers._emulator_banks.cache_clear()
        np.testing.assert_allclose(
            np.asarray(banks1[(20.0, 0.0, 50.0)].alpha),
            np.asarray(banks2[(20.0, 0.0, 50.0)].alpha),
            atol=1e-7,
        )
