"""Off-TPU smoke test of the bench result-assembly path (ISSUE 2
satellite): the BENCH JSON schema is consumed by cross-round dashboards,
so drift must break tier-1 here, not the dashboard."""

import json

import pytest

from kafka_tpu import telemetry
from kafka_tpu.telemetry import MetricsRegistry

import bench

#: The PR 1 artifact key set (BENCH_r*.json), plus PR 2's "telemetry"
#: snapshot.  Health fields byte-identical in schema to PR 1.
EXPECTED_KEYS = [
    "metric", "value", "unit",
    "vs_baseline", "vs_baseline_at_scale",
    "oracle_ms_median", "oracle_ms_spread", "oracle_ms_min",
    "n_pix_device", "n_pix_matched",
    "device_px_s_matched", "device_ms_matched_median",
    "device_ms_matched_spread",
    "device_xla_ms", "device_xla_ms_spread",
    "device_pallas_ms", "device_pallas_ms_spread", "device_pallas_px_s",
    "device_pallas_fused_lin_ms", "device_pallas_fused_lin_ms_spread",
    "device_pallas_fused_lin_px_s",
    "device_smoother_ms", "device_smoother_px_s",
    "e2e_pixel_steps_per_s", "e2e_pixel_steps_per_s_spread",
    "e2e_device_fraction", "e2e_n_pixels",
    "serve_p50_ms", "serve_p99_ms", "serve_cold_ms",
    "serve_rejected_total", "serve_requests_total",
    "serve_smoothed_p50_ms", "serve_smoothed_p99_ms",
    "serve_trace_coverage", "serve_slowest_ms",
    "live_telemetry",
    "serve_fleet_p50_ms", "serve_fleet_p99_ms", "serve_fleet_replicas",
    "serve_fleet_requests_total", "serve_fleet_rerouted_total",
    "serve_backoff_total",
    "serve_slo_alerts_total", "serve_slo_budget_remaining",
    "probe_device_ms", "probe_host_ms", "probe_retried",
    "unhealthy_reasons", "probe_host_after_ms", "unhealthy",
    "serve_sweep", "serve_batched_px_s", "serve_batch_mean_size",
    "serve_queue_wait_p99_ms", "serve_unbatched_p99_ms",
    "serve_unbatched_queue_wait_p99_ms",
    "telemetry", "solver_health", "quality", "perf", "slo",
    "device_profile", "program_contracts",
]

HEALTH_KEYS = {
    "probe_device_ms", "probe_host_ms", "probe_retried",
    "unhealthy", "unhealthy_reasons",
}


#: a tools/loadgen.bench_serve rows dict, as the serving bench emits it.
SERVE_ROWS = {
    "serve_p50_ms": 4.5, "serve_p99_ms": 22.0, "serve_cold_ms": 800.0,
    "serve_rejected_total": 0, "serve_requests_total": 24,
    "serve_ok_total": 24, "serve_cancelled_total": 0,
    "serve_error_total": 0,
    "serve_smoothed_p50_ms": 9.0, "serve_smoothed_p99_ms": 35.0,
    "serve_smoothed_ok_total": 6,
    "serve_trace_coverage": 1.0, "serve_slowest_ms": 25.5,
    "serve_slo_alerts_total": 0, "serve_slo_budget_remaining": 1.0,
    "live_telemetry": {
        "scrape_url": "http://127.0.0.1:1/metrics", "samples": 3,
        "scrape_errors": 0,
        "series": {"kafka_serve_queue_depth": [0.0, 2.0, 0.0]},
    },
}


#: a tools/loadgen.bench_fleet rows dict, as the elastic-fleet bench
#: emits it (ISSUE 13).
FLEET_ROWS = {
    "serve_fleet_p50_ms": 5.1, "serve_fleet_p99_ms": 30.0,
    "serve_fleet_requests_total": 24, "serve_fleet_ok_total": 24,
    "serve_fleet_rejected_total": 0, "serve_fleet_error_total": 0,
    "serve_fleet_rps": 50.0, "serve_fleet_rerouted_total": 0,
    "serve_fleet_replicas": 3, "serve_fleet_cold_ms": 900.0,
    "serve_backoff_total": 0,
}


#: a tools/loadgen.bench_concurrency_sweep dict, as the coalesced-serving
#: bench emits it (ISSUE 20).
SWEEP_ROWS = {
    "serve_sweep": [
        {"concurrency": 1, "serve_p99_ms": 40.0,
         "serve_queue_wait_p99_ms": 1.0, "serve_batch_mean_size": 1.0,
         "serve_batch_coalesced_total": 0, "serve_px_s": 6.0e3},
        {"concurrency": 32, "serve_p99_ms": 210.0,
         "serve_queue_wait_p99_ms": 160.0, "serve_batch_mean_size": 7.5,
         "serve_batch_coalesced_total": 30, "serve_px_s": 1.1e4},
    ],
    "serve_sweep_concurrencies": [1, 32],
    "serve_batched_px_s": 1.1e4,
    "serve_batch_mean_size": 7.5,
    "serve_queue_wait_p99_ms": 160.0,
    "serve_unbatched_p99_ms": 260.0,
    "serve_unbatched_queue_wait_p99_ms": 240.0,
}


#: a bench.bench_smoother_rows dict, as the reanalysis bench emits it.
SMOOTHER_ROWS = {
    "device_smoother_ms": 12.5,
    "device_smoother_px_s": 1.05e7,
}


def _assemble(reg, host_after_ms=0.3, serve=SERVE_ROWS,
              fleet=FLEET_ROWS, smoother=SMOOTHER_ROWS,
              sweep=SWEEP_ROWS):
    health = bench.probe_health(retry_wait_s=0.0, registry=reg)
    return health, bench.assemble_result(
        health,
        oracle=(1.0e5, 160.0, 12.0, 154.0),
        device_matched=(2.0e6, 8.0, 0.5),
        device=(8.2e7, 6.4, 0.05),
        pallas=None,           # off-TPU: the Pallas rows are never measured
        fused_lin=None,
        e2e=(5.0e4, 0.55, 7212, 1.2e4),
        serve=serve,
        fleet=fleet,
        smoother=smoother,
        sweep=sweep,
        host_after_ms=host_after_ms,
        registry=reg,
    )


class TestBenchArtifactSchema:
    def test_key_set_matches_pr1_plus_telemetry(self):
        with telemetry.use(MetricsRegistry()) as reg:
            health, result = _assemble(reg)
        assert set(result.keys()) == set(EXPECTED_KEYS)
        # Health fields: schema byte-identical to the PR 1 artifact.
        assert HEALTH_KEYS <= set(health.keys())
        for k in HEALTH_KEYS:
            assert result[k] == health[k] or k == "unhealthy"

    def test_pallas_fields_null_off_tpu(self):
        import jax

        assert jax.default_backend() != "tpu"  # the suite pins CPU
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["device_pallas_ms"] is None
        assert result["device_pallas_ms_spread"] is None
        assert result["device_pallas_px_s"] is None
        assert result["device_pallas_fused_lin_ms"] is None
        assert result["device_pallas_fused_lin_ms_spread"] is None
        assert result["device_pallas_fused_lin_px_s"] is None
        assert result["probe_device_ms"] is None

    def test_telemetry_snapshot_carries_health_gauges(self):
        """probe_health records into — and reads back from — the
        registry: the bench artifact's telemetry snapshot must carry the
        exact probe reading the health verdict was made from."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
            host_gauge = reg.value("kafka_health_probe_host_ms")
        tel = result["telemetry"]
        assert tel["kafka_health_probe_host_ms"] == host_gauge
        assert round(host_gauge, 3) == result["probe_host_ms"]
        assert "kafka_health_unhealthy" in tel

    def test_solver_health_snapshot_always_present(self):
        """The solver-health snapshot rides every artifact (zeros on a
        healthy run) and sums labelled series, so bench_compare can
        diff result quality without special-casing missing keys."""
        with telemetry.use(MetricsRegistry()) as reg:
            reg.counter(
                "kafka_solver_quarantined_pixels_total", "t"
            ).inc(3)
            reg.counter(
                "kafka_solver_clip_saturated_total", "t"
            ).inc(2, param="lai")
            reg.counter(
                "kafka_solver_clip_saturated_total", "t"
            ).inc(1, param="sm")
            _, result = _assemble(reg)
        snap = result["solver_health"]
        assert snap["quarantined_pixels"] == 3
        assert snap["clip_saturated"] == 3  # summed over param labels
        assert snap["cap_bailouts"] == 0  # present even when unseen
        with telemetry.use(MetricsRegistry()) as reg:
            _, clean = _assemble(reg)
        assert set(clean["solver_health"]) >= {
            "quarantined_pixels", "cap_bailouts", "damped_recoveries",
            "nonfinite", "clip_saturated",
        }
        assert all(v == 0 for v in clean["solver_health"].values())

    def test_quality_snapshot_always_present(self):
        """The assimilation-quality snapshot rides every artifact (a
        null verdict + zero window counts on a run that recorded no
        quality windows) so bench_compare can diff consistency without
        special-casing missing keys — the solver_health twin."""
        from kafka_tpu.telemetry import quality as q

        with telemetry.use(MetricsRegistry()) as reg:
            _, clean = _assemble(reg)
        snap = clean["quality"]
        assert set(snap) == {
            "verdict", "windows", "drift_events", "drift_active",
        }
        assert snap["verdict"] is None
        assert set(snap["windows"]) == set(q.VERDICTS)
        assert all(v == 0 for v in snap["windows"].values())
        assert snap["drift_events"] == 0 and snap["drift_active"] == 0
        # A run that recorded windows carries their verdict counts and
        # the worst verdict as the overall one.
        with telemetry.use(MetricsRegistry()) as reg:
            led = q.get_ledger(reg)
            led.record_window("2021-01-01", [0.9, 1.1], n_valid=10)
            led.record_window("2021-01-02", [44.0, 1.0], n_valid=10)
            _, result = _assemble(reg)
        snap = result["quality"]
        assert snap["windows"][q.CONSISTENT] == 1
        assert snap["windows"][q.OVERCONFIDENT] == 1
        assert snap["verdict"] == q.OVERCONFIDENT

    def test_slo_snapshot_always_present(self):
        """The SLO snapshot rides every artifact (the stable disabled
        shape when no evaluator ran) so bench_compare can diff alert
        state without special-casing missing keys — the quality twin
        (ISSUE 15)."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, clean = _assemble(reg)
        snap = clean["slo"]
        assert set(snap) == {
            "enabled", "alerts_fired", "alerts_resolved", "firing",
            "objectives",
        }
        assert snap["enabled"] is False
        assert snap["alerts_fired"] == 0 and snap["firing"] == []
        # The serve_slo_* loadgen rows flow through (null without a
        # serving bench).
        assert clean["serve_slo_alerts_total"] == 0
        assert clean["serve_slo_budget_remaining"] == 1.0
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg, serve=None)
        assert result["serve_slo_alerts_total"] is None
        assert result["serve_slo_budget_remaining"] is None
        # An artifact assembled while an engine is bound carries its
        # per-objective budget view.
        from kafka_tpu.telemetry import slo

        with telemetry.use(MetricsRegistry()) as reg:
            eng = slo.get_engine(reg)
            eng.evaluate_once(now=100.0)
            _, result = _assemble(reg)
        snap = result["slo"]
        assert snap["enabled"] is True
        assert set(snap["objectives"]) == {
            "availability", "latency", "quality", "solver", "perf",
        }
        for o in snap["objectives"].values():
            assert o["budget_remaining"] == 1.0

    def test_device_profile_snapshot_always_present(self):
        """The device-plane snapshot rides every artifact (ISSUE 18):
        zeros/None before any capture was parsed, the ranked kernel
        table and collective fraction after one — so bench_compare can
        diff where device time went without special-casing keys."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, clean = _assemble(reg)
        snap = clean["device_profile"]
        assert set(snap) == {
            "captures_parsed", "device_ms", "collective_fraction",
            "kernels", "hbm_peak_bytes", "live_buffer_bytes",
        }
        assert snap["captures_parsed"] == 0
        assert snap["kernels"] == []
        assert snap["collective_fraction"] is None

    def test_program_contracts_snapshot_always_present(self):
        """The program-contract snapshot rides every artifact (ISSUE
        19): per-program trace fingerprints + the contract finding
        count, so bench_compare can warn when two artifacts measured
        DIFFERENT device programs under the same names.  Cached after
        the first assembly — the registry is process-constant."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        snap = result["program_contracts"]
        assert set(snap) == {"programs", "findings", "clean", "error"}
        assert snap["error"] is None
        assert snap["clean"] is True and snap["findings"] == 0
        assert len(snap["programs"]) >= 14
        assert all(
            isinstance(fp, str) and len(fp) == 16
            for fp in snap["programs"].values()
        )
        # cached: the second artifact reuses the same snapshot object.
        with telemetry.use(MetricsRegistry()) as reg:
            _, again = _assemble(reg)
        assert again["program_contracts"] is snap

    def test_json_serialisable_one_line(self):
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        line = json.dumps(result)
        assert "\n" not in line
        assert json.loads(line)["metric"] == "assimilation_throughput"

    def test_unhealthy_flag_closes_the_bracket(self):
        """A host that degraded DURING the run flags the artifact even
        when the opening probe was healthy."""
        with telemetry.use(MetricsRegistry()) as reg:
            health = bench.probe_health(retry_wait_s=0.0, registry=reg)
            result = bench.assemble_result(
                health,
                oracle=(1.0e5, 160.0, 12.0, 154.0),
                device_matched=(2.0e6, 8.0, 0.5),
                device=(8.2e7, 6.4, 0.05),
                pallas=None,
                e2e=(5.0e4, 0.55, 7212),
                host_after_ms=bench.HEALTHY_HOST_MS * 10,
                registry=reg,
            )
        assert result["unhealthy"] is True

    def test_numbers_flow_through(self):
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["value"] == 8.2e7
        assert result["vs_baseline"] == pytest.approx(20.0)
        assert result["vs_baseline_at_scale"] == pytest.approx(820.0)
        assert result["e2e_n_pixels"] == 7212
        assert result["oracle_ms_min"] == 154.0

    def test_serve_rows_flow_through(self):
        """The tools/loadgen serving rows land verbatim; a run whose
        serving bench failed degrades them to null (the gate in
        bench_compare then treats disappearance as a regression)."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["serve_p50_ms"] == 4.5
        assert result["serve_p99_ms"] == 22.0
        assert result["serve_cold_ms"] == 800.0
        assert result["serve_rejected_total"] == 0
        assert result["serve_requests_total"] == 24
        assert result["serve_trace_coverage"] == 1.0
        assert result["serve_slowest_ms"] == 25.5
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg, serve=None)
        assert result["serve_p50_ms"] is None
        assert result["serve_p99_ms"] is None
        assert result["serve_rejected_total"] is None
        assert result["serve_trace_coverage"] is None
        assert result["serve_slowest_ms"] is None
        assert result["live_telemetry"] is None

    def test_fleet_rows_flow_through(self):
        """The elastic-fleet rows (tools/loadgen.bench_fleet) land
        verbatim; a run without a fleet bench degrades them to null
        (serve_fleet_p50/p99_ms disappearance then gates in
        bench_compare like the single-daemon rows)."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["serve_fleet_p50_ms"] == 5.1
        assert result["serve_fleet_p99_ms"] == 30.0
        assert result["serve_fleet_replicas"] == 3
        assert result["serve_fleet_rerouted_total"] == 0
        assert result["serve_backoff_total"] == 0
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg, fleet=None)
        assert result["serve_fleet_p50_ms"] is None
        assert result["serve_fleet_p99_ms"] is None
        assert result["serve_fleet_rerouted_total"] is None

    def test_smoother_rows_flow_through(self):
        """The reanalysis rows (bench_smoother_rows + the loadgen
        --smoothed mix) land verbatim; a run without them degrades to
        null (device_smoother_ms / serve_smoothed_p99_ms disappearance
        then gates in bench_compare)."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["device_smoother_ms"] == 12.5
        assert result["device_smoother_px_s"] == 1.05e7
        assert result["serve_smoothed_p50_ms"] == 9.0
        assert result["serve_smoothed_p99_ms"] == 35.0
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg, serve=None, smoother=None)
        assert result["device_smoother_ms"] is None
        assert result["device_smoother_px_s"] is None
        assert result["serve_smoothed_p50_ms"] is None
        assert result["serve_smoothed_p99_ms"] is None

    def test_sweep_rows_flow_through(self):
        """The coalesced-serving concurrency-sweep rows (tools/loadgen
        bench_concurrency_sweep) land verbatim; a run without a sweep
        degrades them to null (serve_batched_px_s disappearance then
        gates in bench_compare like the other throughput rows)."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["serve_batched_px_s"] == 1.1e4
        assert result["serve_batch_mean_size"] == 7.5
        assert result["serve_queue_wait_p99_ms"] == 160.0
        assert result["serve_unbatched_p99_ms"] == 260.0
        assert result["serve_unbatched_queue_wait_p99_ms"] == 240.0
        assert [r["concurrency"] for r in result["serve_sweep"]] == \
            [1, 32]
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg, sweep=None)
        assert result["serve_sweep"] is None
        assert result["serve_batched_px_s"] is None
        assert result["serve_batch_mean_size"] is None
        assert result["serve_queue_wait_p99_ms"] is None
        assert result["serve_unbatched_p99_ms"] is None
        assert result["serve_unbatched_queue_wait_p99_ms"] is None

    def test_live_telemetry_flows_through(self):
        """The mid-run /metrics scrape series (tools/loadgen) lands
        verbatim in the artifact for bench_compare's informational
        diff."""
        with telemetry.use(MetricsRegistry()) as reg:
            _, result = _assemble(reg)
        assert result["live_telemetry"]["samples"] == 3
        assert "kafka_serve_queue_depth" in \
            result["live_telemetry"]["series"]

    def test_fused_lin_row_flows_through_on_tpu_artifacts(self):
        """When the TPU bench measures the in-kernel generation, its
        triple lands as the device_pallas_fused_lin_* rows (the
        acceptance row: fused_lin < pallas on a healthy artifact)."""
        with telemetry.use(MetricsRegistry()) as reg:
            health = bench.probe_health(retry_wait_s=0.0, registry=reg)
            result = bench.assemble_result(
                health,
                oracle=(1.0e5, 160.0, 12.0, 154.0),
                device_matched=(2.0e6, 8.0, 0.5),
                device=(8.2e7, 6.4, 0.05),
                pallas=(1.4e8, 3.8, 0.04),
                fused_lin=(2.6e8, 2.0, 0.03),
                e2e=(5.0e4, 0.55, 7212),
                host_after_ms=0.3,
                registry=reg,
            )
        assert result["device_pallas_fused_lin_ms"] == 2.0
        assert result["device_pallas_fused_lin_px_s"] == 2.6e8
        assert result["device_pallas_fused_lin_ms"] < \
            result["device_pallas_ms"]
