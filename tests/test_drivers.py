"""End-to-end driver tests: the three reference configurations running
against generated on-disk granule trees (VERDICT round-1 item 2).

Each test builds a physically-consistent data tree (forward model at a
known truth), a state-mask GeoTIFF, runs the CLI main(), and checks
per-chunk outputs, restart markers, and that the analysis moved toward
the truth.
"""

import datetime
import glob
import os

import numpy as np
import pytest

from kafka_tpu.io.geotiff import GeoInfo, read_geotiff, write_geotiff
from kafka_tpu.testing.fixtures import (
    make_mcd43_series,
    make_mod09_granules,
    make_pivot_mask,
    make_s2_granule_tree,
)

GEO = GeoInfo(
    geotransform=(576000.0, 10.0, 0.0, 4325000.0, 0.0, -10.0),
    projection="WGS 84 / UTM zone 30N",
    epsg=32630,
)


def write_mask(path, ny, nx, seed=3):
    mask = make_pivot_mask(ny, nx, n_pivots=3, seed=seed)
    write_geotiff(path, mask.astype(np.uint8), GEO)
    return mask


def day(y, m, d):
    return datetime.datetime(y, m, d)


class TestS2Driver:
    def test_end_to_end(self, tmp_path):
        from kafka_tpu.cli.run_s2 import default_config, main

        ny, nx = 48, 80  # two 48x40-ish chunks with chunk_size 40
        data = str(tmp_path / "s2")
        outdir = str(tmp_path / "out")
        mask_path = str(tmp_path / "pivots.tif")
        mask = write_mask(mask_path, ny, nx)
        truth = make_s2_granule_tree(
            data, [day(2017, 7, 4), day(2017, 7, 6), day(2017, 7, 8)],
            ny=ny, nx=nx, geo=GEO, noise=0.002,
        )

        cfg = default_config()
        cfg.chunk_size = (40, 48)
        cfg.pad_multiple = 64
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)

        stats = main([
            "--config", cfg_path, "--data-folder", data,
            "--state-mask", mask_path, "--outdir", outdir,
        ])
        assert stats["run"] >= 2  # at least two non-trivial chunks ran
        tifs = glob.glob(os.path.join(outdir, "*.tif"))
        assert tifs, "driver wrote no GeoTIFFs"
        markers = glob.glob(os.path.join(outdir, ".chunk_*.done"))
        assert len(markers) == stats["run"] + stats["skipped"]

        # Mosaic the per-chunk TLAI outputs of the last timestep and check
        # the analysis moved from the prior toward the truth.
        date_tag = "A2017190"  # grid step 2017-07-09 window covers Jul 8
        tlai_truth = float(truth[6])
        mosaics = []
        for f in glob.glob(os.path.join(outdir, f"lai_{date_tag}_*.tif")):
            if f.endswith("_unc.tif"):
                continue
            arr, _ = read_geotiff(f)
            mosaics.append(np.asarray(arr))
        assert mosaics, "no lai outputs for the final grid date"
        vals = np.concatenate([m[m > 0] for m in mosaics])
        assert vals.size > 0
        prior_tlai = np.exp(-4.0 / 2.0)  # SAIL prior LAI 4
        assert abs(np.median(vals) - tlai_truth) < \
            abs(prior_tlai - tlai_truth)

    def test_restart_skips_done_chunks(self, tmp_path):
        from kafka_tpu.cli.run_s2 import default_config, main

        ny, nx = 32, 32
        data = str(tmp_path / "s2")
        outdir = str(tmp_path / "out")
        mask_path = str(tmp_path / "pivots.tif")
        write_mask(mask_path, ny, nx)
        make_s2_granule_tree(data, [day(2017, 7, 4)], ny=ny, nx=nx, geo=GEO)

        cfg = default_config()
        cfg.chunk_size = (32, 32)
        cfg.pad_multiple = 64
        cfg.end = datetime.datetime(2017, 7, 5)
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)
        args = ["--config", cfg_path, "--data-folder", data,
                "--state-mask", mask_path, "--outdir", outdir]
        stats1 = main(args)
        assert stats1["run"] == 1
        stats2 = main(args)
        assert stats2["run"] == 0 and stats2["skipped"] == 1


class TestMODISDriver:
    def _make(self, tmp_path, ny=40, nx=40):
        data = str(tmp_path / "mcd43")
        os.makedirs(data, exist_ok=True)
        outdir = str(tmp_path / "out")
        mask_path = str(tmp_path / "mask.tif")
        mask = write_mask(mask_path, ny, nx)
        dates = [
            day(2017, 1, 1) + datetime.timedelta(days=i)
            for i in range(0, 64, 8)
        ]
        truth = make_mcd43_series(
            data, dates, ny=ny, nx=nx, geo=GEO, noise=0.001
        )
        return data, outdir, mask_path, mask, truth

    def test_serial_end_to_end(self, tmp_path):
        from kafka_tpu.cli.run_modis import default_config, main

        data, outdir, mask_path, mask, truth = self._make(tmp_path)
        cfg = default_config()
        cfg.end = datetime.datetime(2017, 3, 1)
        cfg.pad_multiple = 64
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)

        stats = main([
            "--config", cfg_path, "--data-folder", data,
            "--state-mask", mask_path, "--outdir", outdir,
        ])
        assert stats["run"] == 1  # whole tile, one chunk
        telai_files = [
            f for f in glob.glob(os.path.join(outdir, "TeLAI_*.tif"))
            if "_unc" not in f
        ]
        assert telai_files
        arr, _ = read_geotiff(sorted(telai_files)[-1])
        vals = np.asarray(arr)[mask]
        vals = vals[vals > 0]
        # truth TeLAI 0.5; prior 2.0 in LAI -> TLAI exp(-1) ~ 0.368
        assert abs(np.median(vals) - truth[6]) < abs(
            np.exp(-1.0) - truth[6]
        )

    def test_distributed_end_to_end(self, tmp_path):
        from kafka_tpu.cli.run_modis_distributed import (
            default_config,
            main,
        )

        data, outdir, mask_path, mask, truth = self._make(tmp_path)
        cfg = default_config()
        cfg.end = datetime.datetime(2017, 2, 1)
        cfg.chunk_size = (20, 20)   # 4 chunks over the 40x40 tile
        cfg.pad_multiple = 64
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)

        base_args = ["--config", cfg_path, "--data-folder", data,
                     "--state-mask", mask_path, "--outdir", outdir]
        # Two "processes" splitting the chunk set round-robin, run in turn
        # (the scheduler's assignment is deterministic and coordination-free).
        stats0 = main(base_args + ["--num-processes", "2",
                                   "--process-index", "0"])
        stats1 = main(base_args + ["--num-processes", "2",
                                   "--process-index", "1"])
        assert stats0["assigned"] == 2 and stats1["assigned"] == 2
        assert stats0["run"] + stats1["run"] == 4
        markers = glob.glob(os.path.join(outdir, ".chunk_*.done"))
        assert len(markers) == 4
        # Per-chunk prefixed outputs exist for chunks with valid pixels.
        prefixed = glob.glob(os.path.join(outdir, "TeLAI_*_*.tif"))
        assert prefixed


class TestMOD09Driver:
    def test_end_to_end(self, tmp_path):
        from kafka_tpu.cli.run_mod09 import default_config, main

        ny, nx = 8, 8  # 1 km grid -> 16x16 state grid at 500 m
        data = str(tmp_path / "mod09")
        os.makedirs(data, exist_ok=True)
        outdir = str(tmp_path / "out")
        mask_path = str(tmp_path / "mask.tif")
        mask = np.ones((2 * ny, 2 * nx), bool)
        write_geotiff(mask_path, mask.astype(np.uint8), GEO)
        dates = [day(2017, 6, 1) + datetime.timedelta(days=2 * i)
                 for i in range(6)]
        truth = make_mod09_granules(
            data, dates, ny=ny, nx=nx, noise=0.002, seed=5, geo=GEO
        )

        cfg = default_config()
        cfg.end = datetime.datetime(2017, 6, 15)
        cfg.chunk_size = (16, 16)
        cfg.pad_multiple = 64
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)

        stats = main([
            "--config", cfg_path, "--data-folder", data,
            "--state-mask", mask_path, "--outdir", outdir,
        ])
        assert stats["run"] == 1
        iso_files = [
            f for f in glob.glob(os.path.join(outdir, "b1_iso_*.tif"))
            if "_unc" not in f
        ]
        assert iso_files, "driver wrote no kernel-weight outputs"
        arr, _ = read_geotiff(sorted(iso_files)[-1])
        vals = np.asarray(arr)[mask]
        # truth b1 iso = 0.05; the weak prior starts at 0.15
        assert abs(np.median(vals) - truth[0]) < 0.02


class TestJointDriver:
    def test_end_to_end(self, tmp_path):
        """S2+S1 joint CLI: both sensor trees on disk, one chunked run,
        soil-moisture outputs pulled from the prior toward the SAR truth."""
        from kafka_tpu.cli.run_joint import default_config, main
        from kafka_tpu.testing.fixtures import make_s1_series

        ny, nx = 48, 48
        data = str(tmp_path / "s2")
        s1_dir = str(tmp_path / "s1")
        outdir = str(tmp_path / "out")
        mask_path = str(tmp_path / "pivots.tif")
        write_mask(mask_path, ny, nx)

        lai, sm = 3.0, 0.4
        from kafka_tpu.engine.priors import joint_prior
        truth10 = np.asarray(joint_prior().prior.mean)[:10].copy()
        truth10[6] = np.exp(-lai / 2.0)
        make_s2_granule_tree(
            data, [day(2017, 7, 4), day(2017, 7, 8)],
            truth_state=truth10, ny=ny, nx=nx, geo=GEO, noise=0.002,
        )
        make_s1_series(
            s1_dir,
            [datetime.datetime(2017, 7, 6, 17, 55)],
            truth_lai=lai, truth_sm=sm, ny=ny, nx=nx, geo=GEO,
            noise=0.01,
        )

        cfg = default_config()
        cfg.chunk_size = (48, 48)
        cfg.pad_multiple = 64
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)
        stats = main([
            "--config", cfg_path, "--data-folder", data,
            "--s1-folder", s1_dir, "--state-mask", mask_path,
            "--outdir", outdir,
        ])
        assert stats["run"] == 1
        sm_files = [
            f for f in glob.glob(os.path.join(outdir, "sm_*.tif"))
            if not f.endswith("_unc.tif")
        ]
        assert sm_files, "joint driver wrote no soil-moisture outputs"
        last = sorted(sm_files)[-1]
        arr, _ = read_geotiff(last)
        vals = np.asarray(arr)[np.asarray(arr) > 0]
        assert vals.size
        # moved from the 0.25 prior toward the 0.4 SAR truth
        assert abs(np.median(vals) - sm) < abs(0.25 - sm)


class TestS1Driver:
    def test_end_to_end(self, tmp_path):
        """SAR-only CLI: WCM state (LAI, SM) retrieved from VV/VH
        backscatter series with a broad prior."""
        from kafka_tpu.cli.run_s1 import default_config, main
        from kafka_tpu.testing.fixtures import make_s1_series

        ny, nx = 40, 40
        s1_dir = str(tmp_path / "s1")
        outdir = str(tmp_path / "out")
        mask_path = str(tmp_path / "mask.tif")
        write_mask(mask_path, ny, nx)
        lai, sm = 3.0, 0.4
        make_s1_series(
            s1_dir,
            [datetime.datetime(2017, 7, 2 + 6 * i, 17) for i in range(3)],
            truth_lai=lai, truth_sm=sm, ny=ny, nx=nx, geo=GEO, noise=0.01,
        )

        cfg = default_config()
        cfg.chunk_size = (40, 40)
        cfg.pad_multiple = 64
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)
        stats = main([
            "--config", cfg_path, "--data-folder", s1_dir,
            "--state-mask", mask_path, "--outdir", outdir,
        ])
        assert stats["run"] == 1
        for param, truth, prior0 in (("sm", sm, 0.25), ("lai", lai, 2.0)):
            files = [
                f for f in glob.glob(os.path.join(outdir, f"{param}_*.tif"))
                if not f.endswith("_unc.tif")
            ]
            assert files, f"no {param} outputs"
            arr, _ = read_geotiff(sorted(files)[-1])
            vals = np.asarray(arr)[np.asarray(arr) > 0]
            assert vals.size
            assert abs(np.median(vals) - truth) < abs(prior0 - truth), param


class TestCheckpointedDriver:
    def test_mid_chunk_resume(self, tmp_path):
        """checkpoint_folder: an interrupted chunk resumes from its latest
        complete checkpoint instead of re-assimilating every date."""
        from kafka_tpu.cli.drivers import prosail_aux_builder, run_config
        from kafka_tpu.cli.run_s2 import default_config

        ny, nx = 32, 32
        data = str(tmp_path / "s2")
        mask_path = str(tmp_path / "pivots.tif")
        write_mask(mask_path, ny, nx)
        dates = [day(2017, 7, 4), day(2017, 7, 6), day(2017, 7, 8)]
        make_s2_granule_tree(data, dates, ny=ny, nx=nx, geo=GEO,
                             noise=0.002)

        def build(end):
            cfg = default_config()
            cfg.chunk_size = (32, 32)
            cfg.pad_multiple = 64
            cfg.data_folder = data
            cfg.state_mask = mask_path
            cfg.output_folder = str(tmp_path / "out")
            cfg.checkpoint_folder = str(tmp_path / "ck")
            cfg.end = end
            return cfg

        # "Crash" after the first two grid windows: run a truncated grid.
        stats1 = run_config(build(datetime.datetime(2017, 7, 7)),
                            aux_builder=prosail_aux_builder)
        assert stats1["dates_assimilated"] == 2
        cks = os.listdir(str(tmp_path / "ck"))
        assert cks and all(c.startswith("0001_state_") for c in cks)

        # Restart with the full grid: the chunk's .done marker is from the
        # truncated run — clear it, as a restarted job with a longer grid
        # would.  Resume must only assimilate the remaining date.
        for m in glob.glob(
            os.path.join(str(tmp_path / "out"), ".chunk_*.done")
        ):
            os.remove(m)
        stats2 = run_config(build(datetime.datetime(2017, 7, 9)),
                            aux_builder=prosail_aux_builder)
        assert stats2["dates_assimilated"] == 1
        tifs = glob.glob(os.path.join(str(tmp_path / "out"),
                                      "lai_A2017190_*.tif"))
        assert tifs, "resumed run wrote no outputs for the final window"


class TestOomRecovery:
    """Device-OOM recovery is process-based: one RESOURCE_EXHAUSTED
    poisons the whole process's device client (measured on the tunneled
    TPU runtime), so the failed chunk and everything after it run in
    fresh subprocesses, splitting 2x2 when a chunk genuinely exceeds
    HBM."""

    @pytest.fixture(autouse=True)
    def _fresh_poison_flag(self):
        from kafka_tpu.cli import drivers

        drivers._DEVICE_POISONED = False
        yield
        drivers._DEVICE_POISONED = False

    def test_oom_splits_via_subprocesses(self, monkeypatch):
        from kafka_tpu.cli import drivers
        from kafka_tpu.cli.chunk_worker import OOM_EXIT_CODE
        from kafka_tpu.io.tiling import Chunk

        sub_calls = []

        def fake_run_one_chunk(cfg, chunk, prefix, *a, **k):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted)."
            )

        def fake_subprocess(cfg, chunk, prefix):
            sub_calls.append((prefix, chunk.nx_valid, chunk.ny_valid))
            if chunk.nx_valid > 64 or chunk.ny_valid > 64:
                return OOM_EXIT_CODE, None
            return 0, {"prefix": prefix,
                       "n_pixels": chunk.nx_valid * chunk.ny_valid,
                       "n_dates_assimilated": 3, "wall_s": 0.5}

        monkeypatch.setattr(drivers, "run_one_chunk", fake_run_one_chunk)
        monkeypatch.setattr(
            drivers, "_run_chunk_subprocess", fake_subprocess
        )

        import tempfile

        outdir = tempfile.mkdtemp()
        stale = os.path.join(outdir, "lai_A2017183_0001.tif")
        keep = os.path.join(outdir, "lai_A2017183_0001a.tif")
        open(stale, "w").close()
        open(keep, "w").close()

        class Cfg:
            output_folder = outdir

        chunk = Chunk(0, 0, 128, 100, 1)
        s = drivers.run_one_chunk_resilient(
            Cfg(), chunk, "0001", None, None
        )
        # partial full-prefix outputs removed before the split; quarter
        # outputs untouched
        assert not os.path.exists(stale)
        assert os.path.exists(keep)
        # full chunk retried in a fresh process first, then 4 quarters
        assert sub_calls[0] == ("0001", 128, 100)
        # dash-separated quarter prefixes: a bare 'a' suffix would be
        # ambiguous with hex chunk ids (the 'keep' file above IS chunk
        # '0001a''s output and must survive the cleanup globs)
        assert sorted(c[0] for c in sub_calls[1:]) == [
            "0001-a", "0001-b", "0001-c", "0001-d"
        ]
        assert all(c[1] <= 64 and c[2] <= 64 for c in sub_calls[1:])
        assert s["oom_split"] and s["n_pixels"] == 128 * 100
        assert s["n_dates_assimilated"] == 3
        assert drivers._DEVICE_POISONED

    def test_poisoned_process_skips_in_process_path(self, monkeypatch):
        from kafka_tpu.cli import drivers
        from kafka_tpu.io.tiling import Chunk

        def boom(*a, **k):
            raise AssertionError("in-process path used after poisoning")

        monkeypatch.setattr(drivers, "run_one_chunk", boom)
        monkeypatch.setattr(
            drivers, "_run_chunk_subprocess",
            lambda cfg, chunk, prefix: (0, {"prefix": prefix,
                                            "n_pixels": 1}),
        )
        drivers._DEVICE_POISONED = True
        s = drivers.run_one_chunk_resilient(
            None, Chunk(0, 0, 32, 32, 1), "0002", None, None
        )
        assert s == {"prefix": "0002", "n_pixels": 1}

    def test_non_oom_errors_propagate(self, monkeypatch):
        from kafka_tpu.cli import drivers
        from kafka_tpu.io.tiling import Chunk

        def fake_run_one_chunk(*a, **k):
            raise ValueError("broken reader")

        monkeypatch.setattr(drivers, "run_one_chunk", fake_run_one_chunk)
        with pytest.raises(ValueError, match="broken reader"):
            drivers.run_one_chunk_resilient(
                None, Chunk(0, 0, 32, 32, 1), "0001", None, None
            )

    def test_chunk_worker_subprocess_end_to_end(self, tmp_path):
        """The real worker entry point: serialise a config, run one chunk
        in a child interpreter (CPU backend via the test env), read the
        summary JSON back, and find its GeoTIFF outputs on disk."""
        import datetime as dt

        from kafka_tpu.cli import drivers
        from kafka_tpu.engine.config import RunConfig
        from kafka_tpu.engine.priors import PROSAIL_PARAMETER_LIST
        from kafka_tpu.io.tiling import Chunk

        dates = [dt.datetime(2017, 7, 1), dt.datetime(2017, 7, 3)]
        make_s2_granule_tree(str(tmp_path / "s2"), dates, ny=48, nx=64)
        write_mask(str(tmp_path / "mask.tif"), 48, 64)
        cfg = RunConfig(
            parameter_list=PROSAIL_PARAMETER_LIST,
            start=dt.datetime(2017, 6, 30), end=dt.datetime(2017, 7, 4),
            step_days=2, operator="prosail", propagator="none",
            prior="sail", chunk_size=(64, 64), observations="sentinel2",
            data_folder=str(tmp_path / "s2"),
            state_mask=str(tmp_path / "mask.tif"),
            output_folder=str(tmp_path / "out"),
            solver_options={"relaxation": 0.7},
            telemetry_dir=str(tmp_path / "tel"),
        )
        rc, summary = drivers._run_chunk_subprocess(
            cfg, Chunk(0, 0, 64, 48, 1), "0001"
        )
        assert rc == 0, summary
        assert summary["n_pixels"] > 0
        tifs = glob.glob(str(tmp_path / "out" / "*_0001*.tif"))
        assert tifs, "worker wrote no outputs"
        # ISSUE 3 satellite: the worker exports its run telemetry into a
        # per-chunk subdirectory (events + metrics + trace timeline).
        chunk_tel = tmp_path / "tel" / "chunk_0001"
        for artifact in ("events.jsonl", "metrics.json", "metrics.prom",
                         "trace.json"):
            assert (chunk_tel / artifact).exists(), artifact
        import json as _json

        snap = _json.load(open(chunk_tel / "metrics.json"))
        assert "kafka_engine_device_reads_total" in snap


class TestMosaic:
    def test_mosaic_reassembles_chunked_run(self, tmp_path):
        """A 2x2-chunked synthetic S2 run mosaicked back together must
        equal the same run executed as ONE chunk, pixel for pixel."""
        import datetime as dt

        from kafka_tpu.cli.drivers import prosail_aux_builder, run_config
        from kafka_tpu.cli.mosaic import main as mosaic_main
        from kafka_tpu.engine.config import RunConfig
        from kafka_tpu.engine.priors import PROSAIL_PARAMETER_LIST

        dates = [dt.datetime(2017, 7, 1), dt.datetime(2017, 7, 3)]
        make_s2_granule_tree(str(tmp_path / "s2"), dates, ny=64, nx=96)
        write_mask(str(tmp_path / "mask.tif"), 64, 96)

        def cfg(chunks, outdir):
            return RunConfig(
                parameter_list=PROSAIL_PARAMETER_LIST,
                start=dt.datetime(2017, 6, 30),
                end=dt.datetime(2017, 7, 4),
                step_days=2, operator="prosail", propagator="none",
                prior="sail", chunk_size=chunks,
                observations="sentinel2",
                data_folder=str(tmp_path / "s2"),
                state_mask=str(tmp_path / "mask.tif"),
                output_folder=str(tmp_path / outdir),
                solver_options={"relaxation": 0.7},
            )

        run_config(cfg((48, 32), "chunked"),
                   aux_builder=prosail_aux_builder)
        run_config(cfg((96, 64), "whole"),
                   aux_builder=prosail_aux_builder)

        written = mosaic_main([
            str(tmp_path / "chunked"), "--param", "lai",
            "--include-unc", "--like", str(tmp_path / "mask.tif"),
        ])
        assert written, "no mosaics written"
        whole_files = sorted(
            glob.glob(str(tmp_path / "whole" / "lai_*.tif"))
        )
        assert whole_files
        for wf in whole_files:
            base = os.path.basename(wf)
            # whole-run name lai_A2017183_0001[_unc].tif ->
            # mosaic lai_A2017183[_unc].tif
            mos_name = base.replace("_0001", "")
            mos = str(tmp_path / "chunked" / "mosaic" / mos_name)
            assert os.path.exists(mos), mos_name
            a, ia = read_geotiff(wf)
            b, ib = read_geotiff(mos)
            assert a.shape == b.shape
            assert ia.geo.geotransform == ib.geo.geotransform
            np.testing.assert_allclose(b, a, rtol=1e-2, atol=2e-3)


class TestDriverMeshMode:
    def test_chunked_s2_driver_on_local_mesh_matches_no_mesh(
        self, tmp_path, eight_cpu_devices
    ):
        """device_mesh='local' through the REAL chunked driver: chunk
        scheduling + engine mesh compose, and per-pixel outputs equal the
        unsharded run's (the production multi-chip configuration,
        exercised on the virtual 8-device CPU mesh)."""
        from kafka_tpu.cli.drivers import prosail_aux_builder, run_config
        from kafka_tpu.cli.run_s2 import default_config

        ny, nx = 32, 48
        data = str(tmp_path / "s2")
        mask_path = str(tmp_path / "pivots.tif")
        write_mask(mask_path, ny, nx)
        make_s2_granule_tree(
            data, [day(2017, 7, 4), day(2017, 7, 6)], ny=ny, nx=nx,
            geo=GEO, noise=0.002,
        )

        def run(mesh_mode, outdir):
            cfg = default_config()
            cfg.data_folder = data
            cfg.state_mask = mask_path
            cfg.output_folder = str(tmp_path / outdir)
            cfg.chunk_size = (32, 24)
            cfg.pad_multiple = 64
            cfg.end = datetime.datetime(2017, 7, 7)
            cfg.device_mesh = mesh_mode
            return run_config(cfg, aux_builder=prosail_aux_builder)

        stats_m = run("local", "out_mesh")
        stats_r = run("none", "out_ref")
        assert stats_m["run"] == stats_r["run"] >= 1
        ref_files = sorted(glob.glob(
            os.path.join(str(tmp_path / "out_ref"), "*.tif")
        ))
        assert ref_files
        for ref in ref_files:
            other = os.path.join(
                str(tmp_path / "out_mesh"), os.path.basename(ref)
            )
            a, _ = read_geotiff(ref)
            b, _ = read_geotiff(other)
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=3e-4,
                err_msg=os.path.basename(ref),
            )


class TestMakeRunMesh:
    def test_modes(self, eight_cpu_devices):
        import jax

        from kafka_tpu.cli.drivers import make_run_mesh
        from kafka_tpu.engine.config import RunConfig

        # make_run_mesh reads jax.local_devices() (the production
        # contract); if a TPU plugin pinned itself as the default
        # backend despite the conftest, building a mesh over real chips
        # could hang on an unhealthy tunnel — skip rather than touch it.
        if jax.local_devices()[0].platform != "cpu":
            pytest.skip("default backend is not the forced-CPU platform")

        def cfg(mode):
            return RunConfig(
                parameter_list=("a",),
                start=datetime.datetime(2020, 1, 1),
                end=datetime.datetime(2020, 1, 2),
                device_mesh=mode,
            )

        assert make_run_mesh(cfg("none")) is None
        # conftest exposes 8 CPU devices -> auto and local build a mesh
        # spanning ALL local devices (the documented contract)
        n_local = len(jax.local_devices())
        mesh_auto = make_run_mesh(cfg("auto"))
        mesh_local = make_run_mesh(cfg("local"))
        assert mesh_auto is not None and mesh_local is not None
        assert mesh_auto.devices.size == n_local
        assert mesh_local.devices.size == n_local
        with pytest.raises(ValueError, match="device_mesh"):
            make_run_mesh(cfg("nonne"))


class TestPallasProductionDefault:
    """engine/config.py: ``use_pallas`` flips to the production default
    for parity-tested operators ONLY when the healthy-window bench
    artifact ROADMAP demands exists (both device rows, fused faster,
    ``unhealthy: false``) — with an explicit opt-out."""

    def _cfg(self, operator="twostream", solver_options=None):
        from kafka_tpu.engine.config import RunConfig

        return RunConfig(
            parameter_list=tuple("abcdefg"),
            start=day(2020, 1, 1),
            end=day(2020, 1, 2),
            operator=operator,
            solver_options=solver_options,
        )

    @staticmethod
    def _artifact(tmp_path, name="bench.json", **over):
        import json

        art = {
            "device_xla_ms": 6.4, "device_pallas_ms": 3.8,
            "device_pallas_fused_lin_ms": 2.1, "unhealthy": False,
        }
        art.update(over)
        path = tmp_path / name
        path.write_text(json.dumps(art))
        return str(path)

    def test_flips_on_with_qualifying_artifact(self, tmp_path,
                                               monkeypatch):
        from kafka_tpu.engine import config as cfg_mod

        monkeypatch.setenv(
            cfg_mod.BENCH_ARTIFACT_ENV, self._artifact(tmp_path)
        )
        assert cfg_mod.pallas_default_ready()
        assert self._cfg().resolved_solver_options() == {
            "use_pallas": True
        }

    def test_gate_rejects_unhealthy_and_partial_artifacts(self, tmp_path,
                                                          monkeypatch):
        from kafka_tpu.engine import config as cfg_mod

        cases = [
            self._artifact(tmp_path, "unhealthy.json", unhealthy=True),
            self._artifact(tmp_path, "no_pallas.json",
                           device_pallas_ms=None),
            self._artifact(tmp_path, "pre_health.json", unhealthy=None),
            self._artifact(tmp_path, "slower.json", device_pallas_ms=7.0),
        ]
        for path in cases:
            monkeypatch.setenv(cfg_mod.BENCH_ARTIFACT_ENV, path)
            assert not cfg_mod.pallas_default_ready(), path
            assert self._cfg().resolved_solver_options() is None, path

    def test_explicit_opt_out_wins(self, tmp_path, monkeypatch):
        from kafka_tpu.engine import config as cfg_mod

        monkeypatch.setenv(
            cfg_mod.BENCH_ARTIFACT_ENV, self._artifact(tmp_path)
        )
        cfg = self._cfg(solver_options={"use_pallas": False})
        assert cfg.resolved_solver_options() == {"use_pallas": False}

    def test_untested_operator_never_flips(self, tmp_path, monkeypatch):
        from kafka_tpu.engine import config as cfg_mod

        monkeypatch.setenv(
            cfg_mod.BENCH_ARTIFACT_ENV, self._artifact(tmp_path)
        )
        cfg = self._cfg(operator="identity")
        assert cfg.resolved_solver_options() is None

    def test_wrapped_artifact_payload_unwrapped(self, tmp_path,
                                                monkeypatch):
        """The driver archives BENCH JSONs wrapped under "parsed"
        (BENCH_r0*.json); the gate must read through the wrapper."""
        import json

        from kafka_tpu.engine import config as cfg_mod

        wrapped = tmp_path / "wrapped.json"
        wrapped.write_text(json.dumps({"n": 9, "parsed": {
            "device_xla_ms": 6.4, "device_pallas_ms": 3.8,
            "unhealthy": False,
        }}))
        monkeypatch.setenv(cfg_mod.BENCH_ARTIFACT_ENV, str(wrapped))
        assert cfg_mod.pallas_default_ready()

    def test_archived_artifacts_do_not_yet_qualify(self, monkeypatch):
        """The repo's CURRENT archived artifacts predate the health
        schema — the default must still be off (the flip is armed, not
        forced).  This test documents the gate state; it flips to
        asserting True once a qualifying artifact is archived, at which
        point the default is live and this guard should be updated."""
        from kafka_tpu.engine import config as cfg_mod

        monkeypatch.delenv(cfg_mod.BENCH_ARTIFACT_ENV, raising=False)
        # Whatever the archive holds, resolved options must be
        # consistent with the gate's verdict.
        ready = cfg_mod.pallas_default_ready()
        resolved = self._cfg().resolved_solver_options()
        assert resolved == ({"use_pallas": True} if ready else None)
