"""The telemetry subsystem: registry semantics, spans, engine wiring, and
the zero-extra-transfer guarantee (ISSUE 2 acceptance: convergence
scalars ride the ONE existing packed device->host read per window)."""

import json
import os
import threading

import numpy as np
import pytest

from kafka_tpu import telemetry
from kafka_tpu.telemetry import MetricsRegistry
from kafka_tpu.telemetry.registry import DEFAULT_BUCKETS


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("kafka_test_counter_total")
        c.inc()
        c.inc(4)
        assert reg.value("kafka_test_counter_total") == 5
        g = reg.gauge("kafka_test_depth")
        g.set(3)
        g.set(1)
        assert reg.value("kafka_test_depth") == 1
        h = reg.histogram("kafka_test_seconds")
        h.observe(0.02)
        h.observe(1.7)
        st = reg.value("kafka_test_seconds")
        assert st["count"] == 2 and abs(st["sum"] - 1.72) < 1e-9
        assert st["min"] == 0.02 and st["max"] == 1.7

    def test_labels_are_distinct_series(self):
        reg = MetricsRegistry()
        c = reg.counter("kafka_test_windows_total")
        c.inc(mode="fused")
        c.inc(2, mode="single")
        assert reg.value("kafka_test_windows_total", mode="fused") == 1
        assert reg.value("kafka_test_windows_total", mode="single") == 2
        assert reg.value("kafka_test_windows_total", mode="other") is None

    def test_name_convention_enforced(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="convention"):
            reg.counter("badName")
        with pytest.raises(ValueError, match="convention"):
            reg.gauge("queue_depth")

    def test_reregistration_same_kind_returns_same_metric(self):
        reg = MetricsRegistry()
        a = reg.counter("kafka_test_again_total")
        b = reg.counter("kafka_test_again_total")
        assert a is b
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("kafka_test_again_total")

    def test_thread_safety_of_counter(self):
        reg = MetricsRegistry()
        c = reg.counter("kafka_test_race_total")

        def spin():
            for _ in range(2000):
                c.inc()

        threads = [threading.Thread(target=spin) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.value("kafka_test_race_total") == 16000

    def test_prom_text_exposition(self):
        reg = MetricsRegistry()
        reg.counter("kafka_test_total", "help line").inc(3, band="b1")
        reg.gauge("kafka_test_depth").set(2.5)
        reg.histogram(
            "kafka_test_seconds", buckets=(0.1, 1.0)
        ).observe(0.5)
        text = reg.prom_text()
        assert '# TYPE kafka_test_total counter' in text
        assert 'kafka_test_total{band="b1"} 3' in text
        assert "kafka_test_depth 2.5" in text
        assert 'kafka_test_seconds_bucket{le="0.1"} 0' in text
        assert 'kafka_test_seconds_bucket{le="1"} 1' in text
        assert 'kafka_test_seconds_bucket{le="+Inf"} 1' in text
        assert "kafka_test_seconds_count 1" in text

    def test_events_jsonl_and_snapshot_dump(self, tmp_path):
        d = str(tmp_path / "tel")
        reg = MetricsRegistry(d)
        reg.emit("solve", date="2021-01-01", n_iterations=3)
        reg.counter("kafka_test_total").inc()
        reg.dump()
        reg.close()
        events = [json.loads(l) for l in open(os.path.join(
            d, "events.jsonl"
        ))]
        assert events[0]["event"] == "solve"
        assert events[0]["n_iterations"] == 3
        assert "ts" in events[0]
        snap = json.load(open(os.path.join(d, "metrics.json")))
        assert snap["kafka_test_total"]["type"] == "counter"
        assert snap["kafka_test_total"]["series"][0]["value"] == 1
        assert os.path.exists(os.path.join(d, "metrics.prom"))

    def test_dump_races_close_single_flush_close(self, tmp_path):
        """The events.jsonl handle must be flushed/closed exactly once
        when dump() races close(): close() detaches the handle under the
        registry lock, dump() tolerates losing the race (no ValueError
        from a closed file), and every pre-close event is on disk."""
        for attempt in range(20):
            d = str(tmp_path / f"tel{attempt}")
            reg = MetricsRegistry(d)
            reg.counter("kafka_test_total").inc()
            for i in range(50):
                reg.emit("tick", i=i)
            errors = []
            barrier = threading.Barrier(4)

            def racer(fn):
                barrier.wait()
                try:
                    for _ in range(5):
                        fn()
                except Exception as exc:  # noqa: BLE001 — test collects
                    errors.append(exc)

            threads = [
                threading.Thread(target=racer, args=(fn,))
                for fn in (reg.dump, reg.dump, reg.close, reg.close)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert errors == []
            assert reg._events_fh is None  # closed exactly once, stays so
            lines = open(os.path.join(d, "events.jsonl")).readlines()
            assert len(lines) == 50  # every pre-close event flushed

    def test_use_swaps_default_registry(self):
        before = telemetry.get_registry()
        with telemetry.use(MetricsRegistry()) as reg:
            assert telemetry.get_registry() is reg
        assert telemetry.get_registry() is before

    def test_default_buckets_sorted(self):
        assert list(DEFAULT_BUCKETS) == sorted(DEFAULT_BUCKETS)


class TestSpan:
    def test_span_records_histogram_and_event(self):
        with telemetry.use(MetricsRegistry()) as reg:
            with telemetry.span("advance"):
                pass
            st = reg.value("kafka_engine_phase_seconds", phase="advance")
            assert st["count"] == 1
            assert reg.events[-1]["event"] == "phase"
            assert reg.events[-1]["phase"] == "advance"

    def test_span_records_on_exception(self):
        """The exception path records ALL sinks: histogram observation,
        JSONL event, and the trace-timeline span — a phase that dies
        still leaves its wall time and its place on the timeline."""
        with telemetry.use(MetricsRegistry()) as reg:
            with pytest.raises(RuntimeError):
                with telemetry.span("assimilate"):
                    raise RuntimeError("boom")
            st = reg.value(
                "kafka_engine_phase_seconds", phase="assimilate"
            )
            assert st["count"] == 1
            assert reg.events[-1]["event"] == "phase"
            assert reg.events[-1]["phase"] == "assimilate"
            assert reg.events[-1]["seconds"] >= 0
            spans = [e for e in reg.trace.to_chrome()["traceEvents"]
                     if e["ph"] == "X"]
            assert [s["name"] for s in spans] == ["assimilate"]


class TestEngineTelemetry:
    def _run(self, scan_window):
        from kafka_tpu.testing.synthetic import run_tip_engine

        with telemetry.use(MetricsRegistry()) as reg:
            kf, out, x_a, p_inv_a = run_tip_engine(
                scan_window=scan_window
            )
        return kf, reg

    def test_convergence_scalars_in_registry_and_log(self):
        kf, reg = self._run(scan_window=1)
        # Every assimilated window carries the full telemetry record.
        for rec in kf.diagnostics_log:
            assert len(rec["chi2_per_band"]) == 2
            assert all(np.isfinite(v) for v in rec["chi2_per_band"])
            assert rec["bounds_clipped"] >= 0
            assert rec["nodata"] >= 0
        n = len(kf.diagnostics_log)
        assert reg.value(
            "kafka_engine_windows_total", mode="single"
        ) == n
        assert reg.value("kafka_engine_gn_iterations")["count"] == n
        assert reg.value(
            "kafka_engine_innovation_chi2", band="0"
        )["count"] == n
        assert reg.value("kafka_engine_bounds_clipped_total") is not None
        assert reg.value("kafka_engine_nodata_pixels_total") > 0
        # 5% synthetic masking over 4 dates x 2 bands: the mean nodata
        # fraction must come out near the masking probability.
        nodata = sum(r["nodata"] for r in kf.diagnostics_log)
        denom = 2 * kf.gather.n_valid * n
        assert 0.01 < nodata / denom < 0.12
        # Phase spans cover the loop.
        for phase in ("advance", "assimilate", "dump"):
            assert reg.value(
                "kafka_engine_phase_seconds", phase=phase
            )["count"] >= 1
        # Prefetch pipeline stats from the same run.
        assert reg.value("kafka_prefetch_reads_total") == n
        assert reg.value("kafka_prefetch_read_seconds")["count"] == n
        assert reg.value("kafka_prefetch_queue_depth") is not None

    def test_fused_blocks_carry_same_telemetry(self):
        kf, reg = self._run(scan_window=4)
        fused = [r for r in kf.diagnostics_log if "fused" in r]
        assert fused, "expected at least one fused block"
        for rec in fused:
            assert len(rec["chi2_per_band"]) == 2
            assert rec["nodata"] >= 0
        assert reg.value(
            "kafka_engine_windows_total", mode="fused"
        ) == len(fused)
        assert reg.value(
            "kafka_engine_phase_seconds", phase="fused_scan"
        )["count"] >= 1

    def test_zero_additional_device_reads_per_window(self):
        """THE acceptance guarantee: telemetry scalars ride the one
        existing packed diagnostic read per solve dispatch — the counted
        fetch_scalars funnel shows exactly one read per unfused window /
        fused block, nothing more."""
        for scan_window in (1, 4):
            kf, reg = self._run(scan_window=scan_window)
            # One packed read per dispatch: each unfused window is one
            # dispatch; a fused block of k windows is one dispatch.
            expected = sum(
                1.0 / rec.get("fused", 1) for rec in kf.diagnostics_log
            )
            assert expected == int(expected)
            reads = reg.value("kafka_engine_device_reads_total")
            assert reads == int(expected), (
                f"scan_window={scan_window}: {reads} packed reads for "
                f"{int(expected)} dispatches"
            )

    def test_fused_and_unfused_telemetry_agree(self):
        """The same problem through the fused scan and the date loop must
        report the same totals (iterations, nodata) — the telemetry is a
        property of the data, not of the execution strategy."""
        kf1, _ = self._run(scan_window=1)
        kf4, _ = self._run(scan_window=4)
        assert len(kf1.diagnostics_log) == len(kf4.diagnostics_log)
        for r1, r4 in zip(kf1.diagnostics_log, kf4.diagnostics_log):
            assert r1["nodata"] == r4["nodata"]
            np.testing.assert_allclose(
                r1["chi2_per_band"], r4["chi2_per_band"],
                rtol=0.05, atol=1e-3,
            )


class TestBandSequentialTelemetry:
    def test_band_sequential_merges_chi2_and_nodata(self):
        import datetime

        import jax.numpy as jnp

        from kafka_tpu.core.propagators import PixelPrior
        from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
        from kafka_tpu.testing import MemoryOutput, SyntheticObservations
        from kafka_tpu.obsops.identity import IdentityOperator

        def day(i):
            return datetime.datetime(2021, 3, 1) + \
                datetime.timedelta(days=i)

        rng = np.random.default_rng(0)
        mask = np.ones((6, 6), bool)
        p = 2
        op = IdentityOperator(n_params=p, obs_indices=(0, 1))
        truth = rng.uniform(
            0.3, 0.7, mask.shape + (p,)
        ).astype(np.float32)
        obs = SyntheticObservations(
            dates=[day(1), day(2)], operator=op,
            truth_fn=lambda date: truth, sigma=0.02, seed=5,
        )
        mean = np.full((p,), 0.5, np.float32)
        cov = np.diag(np.full((p,), 0.25)).astype(np.float32)
        prior = FixedGaussianPrior(
            PixelPrior(
                mean=jnp.asarray(mean), cov=jnp.asarray(cov),
                inv_cov=jnp.asarray(np.linalg.inv(cov)),
            ),
            ("a", "b"),
        )
        with telemetry.use(MetricsRegistry()) as reg:
            kf = KalmanFilter(
                obs, MemoryOutput(), mask, ("a", "b"),
                state_propagation=None, prior=prior, pad_multiple=16,
                band_sequential=True, prefetch_depth=0,
            )
            kf.set_trajectory_model()
            kf.set_trajectory_uncertainty(np.zeros(p, np.float32))
            x0, p_inv0 = prior.process_prior(None, kf.gather)
            kf.run([day(0), day(3)], x0, None, p_inv0)
        # One merged record per date, chi2 concatenated over BOTH bands.
        assert len(kf.diagnostics_log) == 2
        for rec in kf.diagnostics_log:
            assert len(rec["chi2_per_band"]) == 2
        assert reg.value("kafka_engine_device_reads_total") == 2


class TestOutputWriterTelemetry:
    def test_write_metrics_and_backlog(self, tmp_path):
        from kafka_tpu.engine.state import make_pixel_gather
        from kafka_tpu.io import GeoTIFFOutput
        from kafka_tpu.testing.fixtures import DEFAULT_GEO

        import datetime

        gather = make_pixel_gather(np.ones((8, 8), bool), pad_multiple=64)
        x = np.random.default_rng(0).uniform(
            size=(gather.n_pad, 2)
        ).astype(np.float32)
        with telemetry.use(MetricsRegistry()) as reg:
            out = GeoTIFFOutput(
                ("a", "b"), DEFAULT_GEO.geotransform,
                DEFAULT_GEO.projection, folder=str(tmp_path),
                epsg=DEFAULT_GEO.epsg, async_writes=True,
            )
            for i in range(3):
                out.dump_data(
                    datetime.datetime(2021, 3, 1 + i), x, None,
                    gather, ("a", "b"),
                )
            out.close()
            assert reg.value("kafka_io_writes_total") == 3
            assert reg.value("kafka_io_write_seconds")["count"] == 3
            # Drained queue ends at zero backlog.
            assert reg.value("kafka_io_writer_backlog") == 0


class TestSyntheticDriverEndToEnd:
    def test_run_synthetic_writes_telemetry_artifacts(self, tmp_path):
        """ISSUE 2 acceptance: a synthetic end-to-end run with
        --telemetry-dir produces the JSONL event log and a metrics
        snapshot carrying convergence scalars, prefetch queue stats and
        phase wall-times."""
        from kafka_tpu.cli.run_synthetic import main

        tel = str(tmp_path / "tel")
        prev = telemetry.get_registry()
        try:
            main([
                "--operator", "identity",
                "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
                "--days", "8", "--step", "2",
                "--ny", "24", "--nx", "24",
            ])
        finally:
            telemetry.set_registry(prev)
        events = [json.loads(l) for l in open(
            os.path.join(tel, "events.jsonl")
        )]
        kinds = {e["event"] for e in events}
        assert {"solve", "phase", "run_done"} <= kinds
        snap = json.load(open(os.path.join(tel, "metrics.json")))
        for name in (
            "kafka_engine_gn_iterations",
            "kafka_engine_innovation_chi2",
            "kafka_engine_bounds_clipped_total",
            "kafka_engine_nodata_pixels_total",
            "kafka_engine_phase_seconds",
            "kafka_engine_device_reads_total",
            "kafka_prefetch_queue_depth",
            "kafka_prefetch_read_seconds",
            "kafka_io_writes_total",
        ):
            assert name in snap, f"{name} missing from metrics.json"
        prom = open(os.path.join(tel, "metrics.prom")).read()
        assert "kafka_engine_gn_iterations_count" in prom
